"""The ``mixed`` layer: a sum of projections over its inputs.

Reference: paddle/gserver/layers/MixedLayer.cpp plus the Projection family
(FullMatrixProjection.cpp, TransposedFullMatrixProjection.cpp,
TableProjection.cpp, IdentityProjection.cpp (+offset), SliceProjection.cpp,
ScalingProjection.cpp, DotMulProjection.cpp) and the config plane
(config_parser.py:487-858).

TPU-native design: a projection is not a runtime object — each is a small
trace-time function contributing one term to a fused sum.  XLA fuses the
adds into the matmuls, so an N-projection mixed layer is N MXU calls plus
fused elementwise, with no interpreter dispatch.

The conf carries ``attrs["projections"]``: a tuple of plain dicts
``{"kind": ..., "in": input_index, ...kind-specific...}``.  Inputs that are
ordinary layers (e.g. a conv_projection or context_projection layer output,
or an operator output) enter as ``kind="identity"`` terms.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerConf
from paddle_tpu.layers.base import ApplyContext, register_layer


def _proj_params(kind: str, spec: Dict[str, Any], in_size: int, out_size: int,
                 rng) -> Dict[str, Any]:
    if kind == "full_matrix":
        return {"w": init.normal(rng, (in_size, out_size),
                                 spec.get("param_std"))}
    if kind == "trans_full_matrix":
        return {"w": init.normal(rng, (out_size, in_size),
                                 spec.get("param_std"))}
    if kind == "table":
        vocab = spec["vocab"] if "vocab" in spec else in_size
        return {"w": init.normal(rng, (vocab, out_size),
                                 spec.get("param_std"))}
    if kind == "scaling":
        return {"w": init.normal(rng, (1,), 1.0)}
    if kind == "dotmul":
        return {"w": init.normal(rng, (out_size,),
                                 1.0 / max(out_size, 1))}
    return {}


def mixed_init(conf: LayerConf, in_confs: List[LayerConf], rng) -> Dict[str, Any]:
    params: Dict[str, Any] = {}
    for j, spec in enumerate(conf.attrs["projections"]):
        in_size = in_confs[spec["in"]].size
        sub = _proj_params(spec["kind"], spec, in_size, conf.size,
                          jax.random.fold_in(rng, j))
        for k, v in sub.items():
            params[f"p{j}_{k}"] = v
    if conf.bias:
        params["b"] = init.zeros((conf.size,))
    return params


def _apply_proj(spec: Dict[str, Any], p: Dict[str, Any], t: SeqTensor,
                out_size: int) -> jnp.ndarray:
    from paddle_tpu.layers.base import gather_sum_rows, is_sparse_ids

    kind = spec["kind"]
    x = t.data
    if kind == "full_matrix":
        if is_sparse_ids(t, int(p["w"].shape[0])):
            return gather_sum_rows(p["w"], x)
        if not t.is_seq and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        return jnp.matmul(x, p["w"])
    if kind == "trans_full_matrix":
        return jnp.matmul(x, p["w"].T)
    if kind == "table":
        if is_sparse_ids(t, int(p["w"].shape[0])) and x.shape[-1] != 1:
            # multi-id slot (sparse_binary): bag-of-rows sum, the reference
            # TableProjection sparse-row path (TableProjection.cpp selected
            # rows; SparseRowMatrix.h regime)
            return gather_sum_rows(p["w"], x)
        idx = x.astype(jnp.int32)
        if idx.ndim >= 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        # out-of-range ids -> zero row (reference KeMatrixAddRows)
        from paddle_tpu.layers.base import take_rows_or_zero

        return take_rows_or_zero(p["w"], idx)
    if kind == "identity":
        return x
    if kind == "identity_offset":
        off = spec.get("offset", 0)
        return x[..., off:off + out_size]
    if kind == "slice":
        return jnp.concatenate(
            [x[..., b:e] for b, e in spec["slices"]], axis=-1
        )
    if kind == "scaling":
        return p["w"][0] * x
    if kind == "dotmul":
        return x * p["w"]
    raise KeyError(f"unknown projection kind {kind!r}")


@register_layer("mixed", init=mixed_init)
def mixed_apply(conf, params, inputs: List[SeqTensor], ctx: ApplyContext) -> SeqTensor:
    acc = None
    lengths = None
    for j, spec in enumerate(conf.attrs["projections"]):
        t = inputs[spec["in"]]
        if t.is_seq:
            lengths = t.lengths
        p = {k[len(f"p{j}_"):]: v for k, v in params.items()
             if k.startswith(f"p{j}_")}
        y = _apply_proj(spec, p, t, conf.size)
        acc = y if acc is None else acc + y
    if "b" in params:
        b = params["b"]
        if acc.ndim == 4 and b.ndim == 1 and b.shape[0] == (
            acc.shape[1] * acc.shape[2] * acc.shape[3]
        ):
            # conv-projection output stays 4D NHWC; the v1 full-width mixed
            # bias is stored flat CHW (img_conv_b.conf: mixed_layer(
            # bias_attr=True) over conv_projection) — place it accordingly
            b = b.reshape(
                acc.shape[3], acc.shape[1], acc.shape[2]
            ).transpose(1, 2, 0)
        acc = acc + b
    return SeqTensor(acc, lengths)


# ---------------------------------------------------------------------------
# conv_operator — ConvOperator.cpp: convolve input[0] (image) with input[1]
# (per-sample filters produced by another layer); no own parameters.
# ---------------------------------------------------------------------------


@register_layer("conv_op")
def conv_op_apply(conf, params, inputs, ctx):
    from paddle_tpu.layers.conv import to_nhwc

    a = conf.attrs
    img = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    kh, kw, cin, cout = a["filter_h"], a["filter_w"], a["in_c"], a["channels"]
    filt = inputs[1].data.reshape(-1, cout, cin, kh, kw)
    # HWIO per sample; vmap the per-sample conv (each sample has its own
    # filter — the reference loops samples through GemmConv).
    filt = filt.transpose(0, 3, 4, 2, 1)

    sh, sw = a.get("stride_h", 1), a.get("stride_w", 1)
    ph, pw = a.get("pad_h", 0), a.get("pad_w", 0)

    def one(x, w):
        if a.get("trans", False):
            from paddle_tpu.layers.conv import conv_transpose_nhwc

            return conv_transpose_nhwc(
                x[None], w, strides=(sh, sw),
                fh=a["filter_h"], fw=a["filter_w"], ph=ph, pw=pw,
            )[0]
        return jax.lax.conv_general_dilated(
            x[None],
            w,
            window_strides=(sh, sw),
            padding=[(ph, ph), (pw, pw)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )[0]

    out = jax.vmap(one)(img, filt)
    return SeqTensor(out, inputs[0].lengths)
