"""Generation layers: GeneratedInput + beam_search as a first-class layer.

Reference: trainer_config_helpers/layers.py beam_search/GeneratedInput
(~:3590-3700) and the generation mode of RecurrentGradientMachine
(paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp:964
generateSequence, :1393 beamSearch).  The reference re-batches beams on the
host every step; here the whole search is ONE jitted lax.scan on device
(ops/beam.py) embedded in the topology like any other layer, so
``paddle.infer(output_layer=beam, field='id')`` runs generation end to end
on the TPU.

Parameter layout: the step sub-network's parameters live under this layer's
name exactly as a ``recurrent_group`` of the same name would store them, so a
generation topology whose beam layer shares the training group's name and
step function loads trained weights unchanged.  The previous-token embedding
table is this layer's ``@gen_emb`` parameter; copy the training embedding in
with ``parameters.set("<beam_name>.@gen_emb.w", trained.get("<emb_name>.w"))``
(the reference shares it globally by parameter name instead).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerConf, LayerOutput, Topology, auto_name
from paddle_tpu.layers.base import ApplyContext, register_layer
from paddle_tpu.layers.recurrent_group import StaticInput, _group_build


class GeneratedInput:
    """The decoder's own previous output, embedded and fed back each step
    (reference GeneratedInput, layers.py:3590).  `size` is the vocabulary;
    `embedding_size` the embedding width fed to the step function."""

    def __init__(
        self,
        size: int,
        embedding_size: int,
        embedding_name: Optional[str] = None,
    ):
        self.size = size
        self.embedding_size = embedding_size
        self.embedding_name = embedding_name


def beam_search(
    step,
    input: Sequence[Union[GeneratedInput, StaticInput]],
    bos_id: int,
    eos_id: int,
    beam_size: Optional[int] = None,
    max_length: int = 30,
    num_results_per_sample: Optional[int] = None,
    name: Optional[str] = None,
    candidate_adjust_fn=None,
    drop_fn=None,
    norm_fn=None,
) -> LayerOutput:
    """Build a generation layer.  `step` is the same step function a training
    ``recurrent_group`` would use; its GeneratedInput argument receives the
    embedded previous token ([B, embedding_size]), StaticInputs behave as in
    recurrent_group, and ``memory()`` links carry decoder state across steps.
    The step must end in a softmax over the vocabulary.

    The three optional hooks are the user beam-search callback surface
    (reference BeamSearchControlCallbacks, RecurrentGradientMachine.h:70-120
    + diy_beam_search_prob_so .cpp:27) as restricted in-graph functions —
    see ops/beam.py's module docstring for signatures.

    Output: int32 ids [B, N, T] sorted best-first, where N =
    num_results_per_sample if set (trimmed from the K=beam_size searched
    beams) else K; beam scores are exposed as the auxiliary output
    ``<name>@scores`` ([B, N]).
    """
    if beam_size is None:
        from paddle_tpu.utils.flags import get_flag

        beam_size = get_flag("beam_size")
    gens = [i for i in input if isinstance(i, GeneratedInput)]
    statics = [i for i in input if isinstance(i, StaticInput)]
    assert len(gens) == 1, "beam_search needs exactly one GeneratedInput"
    gen = gens[0]
    gname = name or auto_name("beam_search")

    step_args: List[LayerOutput] = []
    gen_conf = LayerConf(
        name=f"{gname}@in0", type="step_input", size=gen.embedding_size, bias=False
    )
    static_confs: List[LayerConf] = []
    # Reference beam_search passes inputs in user order; we keep that order
    # for the step call while storing gen/static roles separately.
    for i in input:
        if isinstance(i, GeneratedInput):
            step_args.append(LayerOutput(gen_conf))
        else:
            conf = LayerConf(
                name=f"{gname}@static{len(static_confs)}",
                type="step_input",
                size=i.input.size,
                bias=False,
                attrs={"static_seq": i.is_seq},
            )
            static_confs.append(conf)
            step_args.append(LayerOutput(conf))

    with _group_build() as gb:
        out = step(*step_args)
    assert not isinstance(out, (list, tuple)), "beam step returns one layer"
    assert out.size == gen.size, (
        f"beam step output size {out.size} != vocabulary {gen.size}"
    )
    sub_topo = Topology([out])

    outer_inputs = [s.input for s in statics] + list(gb.boot_layers.values())
    conf = LayerConf(
        name=gname,
        type="beam_search",
        size=max_length,
        inputs=tuple(o.name for o in outer_inputs),
        bias=False,
        attrs={
            "_sub_topology": sub_topo,
            "_memories": tuple(gb.memories),
            "_gen_placeholder": gen_conf.name,
            "_static_placeholders": tuple(
                (c.name, c.attrs.get("static_seq", False)) for c in static_confs
            ),
            "_output": out.name,
            "vocab": gen.size,
            "emb_size": gen.embedding_size,
            "bos_id": bos_id,
            "eos_id": eos_id,
            "beam_size": beam_size,
            "max_length": max_length,
            **(
                {"num_results": int(num_results_per_sample)}
                if num_results_per_sample
                else {}
            ),
            **(
                {"_candidate_adjust_fn": candidate_adjust_fn}
                if candidate_adjust_fn
                else {}
            ),
            **({"_drop_fn": drop_fn} if drop_fn else {}),
            **({"_norm_fn": norm_fn} if norm_fn else {}),
        },
    )
    return LayerOutput(conf, outer_inputs)


def _bs_init(conf, in_confs, rng):
    from paddle_tpu.core.compiler import CompiledNetwork

    sub = CompiledNetwork(conf.attrs["_sub_topology"])
    r1, r2 = jax.random.split(rng)
    params = sub.init_params(r1)
    params["@gen_emb"] = {
        "w": init.normal(r2, (conf.attrs["vocab"], conf.attrs["emb_size"]))
    }
    return params


@register_layer("beam_search", init=_bs_init, auto_activation=False,
                full_precision=True)
def beam_search_apply(conf, params, inputs, ctx: ApplyContext) -> SeqTensor:
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.ops import beam as beam_ops

    a = conf.attrs
    subnet = CompiledNetwork(a["_sub_topology"])
    memories = a["_memories"]
    static_info = a["_static_placeholders"]
    out_name = a["_output"]

    statics = inputs[: len(static_info)]  # rest are boot layers
    b = statics[0].batch_size if statics else inputs[0].batch_size

    emb_w = params["@gen_emb"]["w"]
    sub_params = {k: v for k, v in params.items() if k != "@gen_emb"}

    init_mem = {}
    for m in memories:
        boot = m.attrs.get("boot")
        if boot is not None:
            init_mem[m.name] = ctx.outputs[boot].data
        else:
            init_mem[m.name] = jnp.zeros((b, m.size), emb_w.dtype)
    # Statics ride the carry so beam_search expands them to B*K rows and the
    # parent-gather keeps them aligned (identical across a sample's beams).
    static_carry = {
        pname: (st if is_seq else SeqTensor(st.data))
        for (pname, is_seq), st in zip(static_info, statics)
    }
    carry0 = {"mem": init_mem, "static": static_carry}

    def step_fn(ids, carry):
        sub_batch = dict(carry["static"])
        sub_batch[a["_gen_placeholder"]] = SeqTensor(jnp.take(emb_w, ids, axis=0))
        for m in memories:
            sub_batch[m.name] = SeqTensor(carry["mem"][m.name])
        outs, _ = subnet.apply(sub_params, sub_batch, train=False)
        new_mem = {m.name: outs[m.attrs["link"]].data for m in memories}
        logits = outs.get(out_name + "@logits")
        if logits is not None:  # stashed pre-softmax: stable log-softmax
            logp = jax.nn.log_softmax(logits.data, axis=-1)
        else:
            logp = jnp.log(jnp.maximum(outs[out_name].data, 1e-9))
        return logp, {"mem": new_mem, "static": carry["static"]}

    seqs, scores = beam_ops.beam_search(
        step_fn,
        carry0,
        batch_size=b,
        beam_size=a["beam_size"],
        vocab_size=a["vocab"],
        bos_id=a["bos_id"],
        eos_id=a["eos_id"],
        max_len=a["max_length"],
        candidate_adjust_fn=a.get("_candidate_adjust_fn"),
        drop_fn=a.get("_drop_fn"),
        norm_fn=a.get("_norm_fn"),
    )
    # num_results_per_sample (reference beam_search arg): keep only the
    # best N of the K beams in the layer output
    n_res = a.get("num_results")
    if n_res is not None and n_res < seqs.shape[1]:
        seqs = seqs[:, :n_res]
        scores = scores[:, :n_res]
    ctx.outputs[conf.name + "@scores"] = SeqTensor(scores)
    return SeqTensor(seqs)
