"""Layer implementation registry.

Replaces the reference's ``Layer::create`` + ``REGISTER_LAYER`` machinery
(reference: paddle/gserver/layers/Layer.h:31,348,452).  A layer here is not a
stateful C++ object but a pair of pure functions:

  * ``init(conf, in_confs, rng) -> params``   — build the parameter pytree
  * ``apply(conf, params, inputs, ctx) -> SeqTensor`` — trace the forward op

``apply`` runs under ``jax.jit`` tracing; there is no per-layer dispatch at
execution time, and the backward pass is derived by ``jax.grad`` over the
whole network instead of per-layer ``backward`` methods.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerConf


@dataclasses.dataclass
class ApplyContext:
    """Trace-time context threaded through layer application."""

    train: bool
    rng: Optional[jax.Array] = None  # folded per-layer for dropout etc.
    # All layer outputs computed so far (lets agent/memory layers peek).
    outputs: Dict[str, SeqTensor] = dataclasses.field(default_factory=dict)
    # Non-trainable per-layer state (e.g. batch-norm moving stats): read from
    # `state`, write updates into `new_state` (functional, no mutation).
    state: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    new_state: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    # Default parameter dtype for compute (bfloat16-friendly).
    dtype: Any = jnp.float32
    # Device mesh mesh-aware layers (ring attention) trace against: the
    # owning trainer's mesh, falling back to the process default.
    mesh: Any = None

    def layer_rng(self, name: str) -> Optional[jax.Array]:
        if self.rng is None:
            return None
        return jax.random.fold_in(self.rng, stable_hash(name))


def stable_hash(name: str) -> int:
    """Process-stable 31-bit hash (Python's str hash is salted per process,
    which would make per-layer RNG folds — and thus parameter init —
    nondeterministic across interpreters)."""
    return zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF


InitFn = Callable[[LayerConf, List[LayerConf], jax.Array], Dict[str, Any]]
ApplyFn = Callable[
    [LayerConf, Dict[str, Any], List[SeqTensor], ApplyContext], SeqTensor
]


StateInitFn = Callable[[LayerConf, List[LayerConf]], Dict[str, Any]]


@dataclasses.dataclass(frozen=True)
class LayerImpl:
    type: str
    init: InitFn
    apply: ApplyFn
    # Builds initial non-trainable state (moving stats); None = stateless.
    init_state: Optional[StateInitFn] = None
    # If True the compiler applies conf.act after `apply`; cost layers and
    # layers that handle activation internally opt out.
    auto_activation: bool = True
    # If True the compiler applies dropout (conf.drop_rate) after activation.
    auto_dropout: bool = True
    # If True the compiler upcasts this layer's float inputs to float32 under
    # mixed precision (cost / log-prob layers whose reductions lose too much
    # in bfloat16).
    full_precision: bool = False


_LAYERS: Dict[str, LayerImpl] = {}


def no_params(conf, in_confs, rng) -> Dict[str, Any]:
    return {}


def register_layer(
    type_name: str,
    init: Optional[InitFn] = None,
    *,
    init_state: Optional[StateInitFn] = None,
    auto_activation: bool = True,
    auto_dropout: bool = True,
    full_precision: bool = False,
):
    """Decorator over the apply function:

        @register_layer("fc", init=fc_init)
        def fc_apply(conf, params, inputs, ctx): ...
    """

    def deco(apply: ApplyFn) -> ApplyFn:
        if type_name in _LAYERS:
            raise ValueError(f"duplicate layer type {type_name!r}")
        _LAYERS[type_name] = LayerImpl(
            type=type_name,
            init=init or no_params,
            apply=apply,
            init_state=init_state,
            auto_activation=auto_activation,
            auto_dropout=auto_dropout,
            full_precision=full_precision,
        )
        return apply

    return deco


def get_layer_impl(type_name: str) -> LayerImpl:
    try:
        return _LAYERS[type_name]
    except KeyError:
        raise KeyError(
            f"unknown layer type {type_name!r}; registered: {sorted(_LAYERS)}"
        ) from None


def registered_layer_types() -> List[str]:
    return sorted(_LAYERS)


# ---------------------------------------------------------------------------
# sparse-id batches (big-vocab sparse_binary slots)
# ---------------------------------------------------------------------------


def is_sparse_ids(t, declared_size: int) -> bool:
    """True when a batch SeqTensor is the PADDED-ID form of a sparse_binary
    slot: integer ids [..., max_nnz] with sentinel == vocab, produced by the
    feeder for vocabularies too large to densify (reference sparse-row
    regime, SparseRowMatrix.h — the TPU-native path is gather-of-touched-
    rows, never a [B, vocab] multi-hot).

    Dispatch is EXACT: the feeder sets SeqTensor.sparse_ids when it builds
    the id form — no shape/dtype heuristics, so ordinary integer tensors
    reaching a projection still fail loudly instead of being bag-summed."""
    return bool(getattr(t, "sparse_ids", False))


def take_rows_or_zero(w, idx):
    """Row lookup where ids outside [0, rows) contribute a ZERO row —
    the reference's table-kernel contract (hl_table_apply.cu
    KeMatrixAddRows skips out-of-bounds ids; providers emit
    0xffffffff == -1 for OOV-ignored tokens).  Explicit mask on purpose:
    jnp.take's clamp mode reads the edge row, and its fill mode WRAPS
    negative ids to real rows (measured on this jax: take([-1], mode="fill")
    returns the last row) — both silently wrong.  Backward scatters nothing
    for masked positions (the multiply-by-zero kills the cotangent)."""
    import jax.numpy as _jnp

    valid = (idx >= 0) & (idx < w.shape[0])
    out = _jnp.take(w, _jnp.where(valid, idx, 0), axis=0)
    return out * valid[..., None].astype(out.dtype)


def gather_sum_rows(w, ids):
    """Bag-of-ids contraction: sum of w's rows per padded id list
    ([..., nnz] int32 -> [..., w.shape[1]]); sentinel ids (== w.shape[0],
    out of range) contribute zero via take's fill mode.  This IS the
    sparse-row matmul of the reference (multi-hot @ W == sum of selected
    rows), with only touched rows read."""
    import jax.numpy as _jnp

    g = _jnp.take(w, ids, axis=0, mode="fill", fill_value=0)
    return _jnp.sum(g, axis=-2)
