"""Layer DSL — the user surface equivalent of
``paddle.trainer_config_helpers.layers`` + ``paddle.v2.layer`` (reference:
python/paddle/trainer_config_helpers/layers.py, python/paddle/v2/layer.py).

Each function returns a :class:`LayerOutput` handle; the graph is gathered by
parent traversal when a :class:`Topology` is built (no mutable global config,
unlike the reference's config_parser).  Output-size bookkeeping (conv
arithmetic, implicit flatten) mirrors config_parser.py cnn_output_size.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Union

import numpy as np

from paddle_tpu import activation as _act_mod
from paddle_tpu.activation import act_name
from paddle_tpu.attr import ExtraAttr, ParamAttr
from paddle_tpu.core.data_types import InputType
from paddle_tpu.core.topology import LayerConf, LayerOutput, Topology, auto_name
from paddle_tpu.pooling import pool_name

# Make the implementation registries import (registers layer types).
from paddle_tpu.layers import base as _base  # noqa: F401
from paddle_tpu.layers import basic as _basic  # noqa: F401
from paddle_tpu.layers import conv as _conv  # noqa: F401
from paddle_tpu.layers import cost as _cost  # noqa: F401
from paddle_tpu.layers import misc as _misc  # noqa: F401
from paddle_tpu.layers import mixed as _mixed_impl  # noqa: F401
from paddle_tpu.layers import sampled as _sampled  # noqa: F401
from paddle_tpu.layers import structured as _structured  # noqa: F401
from paddle_tpu.layers import sequence as _sequence  # noqa: F401
from paddle_tpu.layers.recurrent_group import (  # noqa: F401
    StaticInput,
    SubsequenceInput,
    memory,
    recurrent_group,
)
from paddle_tpu.layers.generation import (  # noqa: F401
    GeneratedInput,
    beam_search,
)
from paddle_tpu.layers import attention as _attention  # noqa: F401
from paddle_tpu.layers import detection as _detection  # noqa: F401
from paddle_tpu.layers import mdlstm as _mdlstm  # noqa: F401
from paddle_tpu.layers import moe as _moe  # noqa: F401
from paddle_tpu.layers import layer_math  # noqa: F401  (also patches LayerOutput operators)


class AggregateLevel:
    """Which nesting level a pooling/selection layer collapses (reference
    trainer_config_helpers/layers.py:248).  TO_NO_SEQUENCE pools each whole
    (outer) sequence to one value; TO_SEQUENCE pools each subsequence of a
    nested input, yielding a plain sequence."""

    TO_NO_SEQUENCE = 0
    TO_SEQUENCE = 1
    # deprecated reference aliases
    EACH_TIMESTEP = 0
    EACH_SEQUENCE = 1


class ExpandLevel:
    """How expand_layer broadcasts (reference layers.py:1704):
    FROM_NO_SEQUENCE expands a per-sample value across a (possibly nested)
    pattern; FROM_SEQUENCE expands a plain sequence across a nested pattern's
    subsequence timesteps."""

    FROM_NO_SEQUENCE = 0
    FROM_SEQUENCE = 1
    FROM_TIMESTEP = 0

Inputish = Union[LayerOutput, Sequence[LayerOutput]]


def _as_list(x: Inputish) -> list:
    if isinstance(x, LayerOutput):
        return [x]
    return list(x)


def _dynamic_width(i: LayerOutput) -> bool:
    """A SIZE-CONSUMING layer (fc, mixed matrix projections) stacked on a
    dynamic-width input — e.g. trans(height=None), whose true width is the
    runtime batch size — cannot know its weight height at build time.  The
    conf gets tagged instead of warned: weights init at the declared static
    size for config parity (the reference keeps the static size too,
    TransLayer config_parser.py:2129, protostr dims 100x100 — and then can
    only RUN at batch == size), and the trainer resolves the true width from
    the first batch via CompiledNetwork.resolve_dynamic_widths."""
    return bool(i.conf.attr("dynamic_size"))


def _extra(layer_attr: Optional[ExtraAttr]):
    drop = layer_attr.drop_rate if layer_attr else 0.0
    shard = layer_attr.shard_axis if layer_attr else None
    return drop, shard


def _set_error_clip(conf: LayerConf, layer_attr: Optional[ExtraAttr]) -> None:
    """Record ExtraAttr.error_clipping_threshold on the conf; the compiler
    clips the cotangent flowing into this layer's output to [-t, t]
    (reference Layer.cpp backwardActivation error clipping)."""
    t = getattr(layer_attr, "error_clipping_threshold", 0.0) if layer_attr else 0.0
    if t:
        conf.attrs["error_clip"] = float(t)


def _param_std(param_attr: Optional[ParamAttr]):
    return param_attr.initial_std if param_attr else None


def _param_name(param_attr: Optional[ParamAttr]):
    """Shared-parameter name (reference global parameter table: layers
    declaring the same ParamAttr name share storage)."""
    return param_attr.name if param_attr else None


def _param_attrs(param_attr: Optional[ParamAttr]) -> dict:
    """The generic per-parameter attr bundle every param_attr-taking layer
    stores: init std, shared-parameter name, pruning hook ratio.  Assembled
    in one place so hooks/sharing work uniformly across layer types."""
    return {
        "param_std": _param_std(param_attr),
        "param_name": _param_name(param_attr),
        "prune_sparsity": _prune_ratio(param_attr),
    }


def _prune_ratio(param_attr: Optional[ParamAttr]):
    """sparsity_ratio of a 'pruning' update hook, or None (reference
    StaticPruningHook — see attr.HookAttribute)."""
    if param_attr is None or param_attr.update_hooks is None:
        return None
    hooks = param_attr.update_hooks
    if not isinstance(hooks, (list, tuple)):
        hooks = [hooks]
    for h in hooks:
        if getattr(h, "type", None) == "pruning":
            return float(h.sparsity_ratio)
    return None


_IMG_ATTR_KEYS = ("out_h", "out_w", "in_h", "in_w", "in_c", "channels")


def _img_passthrough(input: LayerOutput) -> dict:
    """Propagate image-geometry attrs through shape-preserving layers (addto,
    batch_norm, clip, ...) so conv chains keep their spatial metadata —
    the reference keeps this in each LayerConfig's img size fields."""
    a = input.conf.attrs
    out = {}
    c = a.get("channels") or a.get("in_c")
    h = a.get("out_h") or a.get("in_h")
    w = a.get("out_w") or a.get("in_w")
    if c is not None and h is not None:
        out.update(in_c=c, in_h=h, in_w=w, channels=c, out_h=h, out_w=w)
    return out


def cnn_output_size(
    img_size: int, filter_size: int, padding: int, stride: int, caffe_mode: bool = True
) -> int:
    """reference: python/paddle/trainer/config_parser.py cnn_output_size."""
    output = (2 * padding + img_size - filter_size) / float(stride)
    if caffe_mode:
        return 1 + int(math.floor(output))
    return 1 + int(math.ceil(output))


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------


def data(name: str, type: InputType, height: int = 0, width: int = 0,
         feed_dtype=None, feed_scale: float = 0.0,
         feed_shift: float = 0.0) -> LayerOutput:
    """Declare an input slot (reference data_layer, layers.py).  Feeding
    order is DFS from the outputs, or explicit Inputs(...) — see
    Topology.data_layers.

    feed_dtype (e.g. "uint8"): narrow ON-WIRE dtype for a dense slot — the
    DataFeeder packs raw values at this dtype (4x fewer host->device bytes
    for uint8 pixels) and the jitted step casts to the compute float on
    device, applying ``x * feed_scale + feed_shift`` (fused into the first
    consumer by XLA).  feed_scale=0 means "just cast".  The reference's
    providers ship bytes the same way (mnist_bin_part stores uint8;
    DataProvider.h double-buffers raw batches)."""
    attrs = {}
    if height and width:
        attrs.update(in_h=height, in_w=width, in_c=max(type.dim // (height * width), 1))
    if feed_dtype is not None:
        attrs["feed_dtype"] = str(np.dtype(feed_dtype))
    if feed_scale:
        attrs["feed_scale"] = float(feed_scale)
    if feed_shift:
        attrs["feed_shift"] = float(feed_shift)
    conf = LayerConf(
        name=name, type="data", size=type.dim, input_type=type, attrs=attrs, bias=False
    )
    return LayerOutput(conf)


data_layer = data


# ---------------------------------------------------------------------------
# fc
# ---------------------------------------------------------------------------


def fc(
    input: Inputish,
    size: int,
    act=None,
    bias_attr: Union[bool, ParamAttr] = True,
    param_attr: Union[ParamAttr, Sequence[ParamAttr], None] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    ins = _as_list(input)
    dyn_in = tuple(idx for idx, i in enumerate(ins) if _dynamic_width(i))
    drop, shard = _extra(layer_attr)
    if isinstance(param_attr, (list, tuple)):
        # per-input weight attrs (reference fc_layer param_attr list): each
        # input i gets weight w{i}; named attrs share storage by name —
        # including the same name twice within one layer (shared_fc.py)
        assert len(param_attr) == len(ins), (
            f"fc param_attr list length {len(param_attr)} != inputs {len(ins)}"
        )
        attrs = {
            "param_stds": tuple(_param_std(pa) for pa in param_attr),
            "prune_sparsity": _prune_ratio(param_attr[0]),
        }
        pnames = {
            f"w{i}": _param_name(pa)
            for i, pa in enumerate(param_attr)
            if _param_name(pa)
        }
    else:
        attrs = _param_attrs(param_attr)
        shared_name = attrs.pop("param_name", None)
        pnames = (
            {f"w{i}": shared_name for i in range(len(ins))}
            if shared_name
            else {}
        )
    if isinstance(bias_attr, ParamAttr) and bias_attr.name:
        pnames["b"] = bias_attr.name
    if pnames:
        attrs["param_names"] = pnames
    if dyn_in:
        attrs["dynamic_width_in"] = dyn_in
    conf = LayerConf(
        name=name or auto_name("fc_layer"),
        type="fc",
        size=size,
        inputs=tuple(i.name for i in ins),
        act=act_name(act if act is not None else _act_mod.Tanh()),
        bias=bool(bias_attr),
        attrs=attrs,
        drop_rate=drop,
        shard_axis=shard,
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, ins)


fc_layer = fc


def embedding(
    input: LayerOutput,
    size: int,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("embedding"),
        type="embedding",
        size=size,
        inputs=(input.name,),
        bias=False,
        attrs={
            **_param_attrs(param_attr),
            # sparse_update=True row-shards the table over the mesh model
            # axis (the sparse-remote-update path of the reference,
            # RemoteParameterUpdater.h:265 — see parallel/sharding.py)
            "sparse_update": bool(param_attr and param_attr.sparse_update),
        },
        drop_rate=drop,
        shard_axis=shard,
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


embedding_layer = embedding


def addto(
    input: Inputish,
    act=None,
    bias_attr: Union[bool, ParamAttr] = False,
    name: Optional[str] = None,
    layer_attr: Optional[ExtraAttr] = None,
) -> LayerOutput:
    ins = _as_list(input)
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("addto"),
        type="addto",
        size=ins[0].size,
        inputs=tuple(i.name for i in ins),
        act=act_name(act),
        bias=bool(bias_attr),
        attrs=_img_passthrough(ins[0]),
        drop_rate=drop,
        shard_axis=shard,
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, ins)


addto_layer = addto


def concat(input: Sequence[LayerOutput], name: Optional[str] = None, act=None,
           bias_attr=False, layer_attr=None) -> LayerOutput:
    ins = _as_list(input)
    if any(isinstance(i, Projection) for i in ins):
        # reference concat2 (ConcatenateLayer2.cpp): concat of PROJECTIONS —
        # each projection becomes a single-term mixed layer, then an
        # ordinary feature concat
        ins = [
            mixed(input=[i], name=auto_name((name or "concat") + "_proj"))
            if isinstance(i, Projection)
            else i
            for i in ins
        ]
    conf = LayerConf(
        name=name or auto_name("concat"),
        type="concat",
        size=sum(i.size for i in ins),
        inputs=tuple(i.name for i in ins),
        act=act_name(act),
        bias=False,
    )
    return LayerOutput(conf, ins)


concat_layer = concat


def dropout(input: LayerOutput, dropout_rate: float, name: Optional[str] = None) -> LayerOutput:
    """Standalone dropout = addto with drop_rate (reference dropout_layer is
    sugar over ExtraAttr.drop_rate)."""
    conf = LayerConf(
        name=name or auto_name("dropout"),
        type="addto",
        size=input.size,
        inputs=(input.name,),
        bias=False,
        attrs=_img_passthrough(input),
        drop_rate=dropout_rate,
    )
    return LayerOutput(conf, [input])


dropout_layer = dropout


# ---------------------------------------------------------------------------
# image layers
# ---------------------------------------------------------------------------


def _img_attrs(input: LayerOutput, num_channels: Optional[int]):
    a = input.conf.attrs
    in_c = num_channels or a.get("channels") or a.get("in_c")
    in_h = a.get("out_h") or a.get("in_h")
    in_w = a.get("out_w") or a.get("in_w")
    if in_h is None:
        # flat input, CHW order: width = floor(sqrt(pixels)), height =
        # pixels // width (reference config_parser.get_img_size:1157 —
        # square when possible, otherwise the 3x4-style factorization)
        assert in_c, f"num_channels required for flat input {input.name}"
        hw = input.size // in_c
        in_w = int(math.isqrt(hw))
        in_h = hw // in_w
        assert in_h * in_w == hw, (
            f"{input.name}: cannot factor {hw} pixels into height x width "
            f"(got {in_h}x{in_w})"
        )
    return int(in_c), int(in_h), int(in_w)


def img_conv(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    stride: int = 1,
    padding: int = 0,
    groups: int = 1,
    num_channels: Optional[int] = None,
    act=None,
    bias_attr: Union[bool, ParamAttr] = True,
    param_attr: Optional[ParamAttr] = None,
    trans: bool = False,
    caffe_mode: bool = True,
    filter_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
    shared_biases: bool = True,  # v1 per-channel bias sharing: always true here
    layer_type: Optional[str] = None,  # 'exconv'/'cudnn_conv' backend hint: XLA picks
    name: Optional[str] = None,
    layer_attr: Optional[ExtraAttr] = None,
) -> LayerOutput:
    """reference img_conv_layer (layers.py) → ExpandConvLayer/CudnnConvLayer."""
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    # reference accepts (x, y) tuples for filter_size/stride/padding
    if isinstance(filter_size, (list, tuple)):
        filter_size, filter_size_y = filter_size
    if isinstance(stride, (list, tuple)):
        stride, stride_y = stride
    if isinstance(padding, (list, tuple)):
        padding, padding_y = padding
    fh = filter_size_y or filter_size
    fw = filter_size
    sh = stride_y or stride
    sw = stride
    ph = padding_y if padding_y is not None else padding
    pw = padding
    if trans:
        if num_filters % groups or in_c % groups:
            raise ValueError(
                f"transpose conv groups={groups} must divide both in_c "
                f"({in_c}) and num_filters ({num_filters})"
            )
        out_h = _conv.convt_output_size(in_h, fh, ph, sh)
        out_w = _conv.convt_output_size(in_w, fw, pw, sw)
    else:
        out_h = cnn_output_size(in_h, fh, ph, sh, caffe_mode)
        out_w = cnn_output_size(in_w, fw, pw, sw, caffe_mode)
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("conv"),
        type="convt" if trans else "conv",
        size=out_h * out_w * num_filters,
        inputs=(input.name,),
        act=act_name(act if act is not None else _act_mod.Relu()),
        bias=bool(bias_attr),
        attrs={
            "in_c": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "filter_h": fh,
            "filter_w": fw,
            "stride_h": sh,
            "stride_w": sw,
            "pad_h": ph,
            "pad_w": pw,
            "groups": groups,
            **_param_attrs(param_attr),
            "channels": num_filters,
            "out_h": out_h,
            "out_w": out_w,
        },
        drop_rate=drop,
        shard_axis=shard,
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


img_conv_layer = img_conv


def img_pool(
    input: LayerOutput,
    pool_size: int,
    stride: int = 1,
    padding: int = 0,
    pool_type=None,
    num_channels: Optional[int] = None,
    pool_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
    ceil_mode: bool = True,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference img_pool_layer → PoolLayer; v1 uses ceil output sizing."""
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    kh = pool_size_y or pool_size
    kw = pool_size
    sh = stride_y or stride
    sw = stride
    ph = padding_y if padding_y is not None else padding
    pw = padding
    out_h = cnn_output_size(in_h, kh, ph, sh, caffe_mode=not ceil_mode)
    out_w = cnn_output_size(in_w, kw, pw, sw, caffe_mode=not ceil_mode)
    conf = LayerConf(
        name=name or auto_name("pool"),
        type="pool",
        size=out_h * out_w * in_c,
        inputs=(input.name,),
        bias=False,
        attrs={
            "in_c": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "filter_h": kh,
            "filter_w": kw,
            "stride_h": sh,
            "stride_w": sw,
            "pad_h": ph,
            "pad_w": pw,
            "pool_type": pool_name(pool_type),
            "channels": in_c,
            "out_h": out_h,
            "out_w": out_w,
        },
    )
    return LayerOutput(conf, [input])


img_pool_layer = img_pool


def batch_norm(
    input: LayerOutput,
    act=None,
    num_channels: Optional[int] = None,
    epsilon: float = 1e-5,
    moving_average_fraction: float = 0.9,
    use_global_stats: Optional[bool] = None,
    bias_attr=True,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    batch_norm_type: Optional[str] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    a = input.conf.attrs
    img = (a.get("out_h") or a.get("in_h")) is not None
    if img:
        in_c, in_h, in_w = _img_attrs(input, num_channels)
        attrs = {
            **_param_attrs(param_attr),
            "channels": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "in_c": in_c,
            "out_h": in_h,
            "out_w": in_w,
        }
    else:
        attrs = {"channels": num_channels or input.size}
    attrs.update(
        epsilon=epsilon,
        moving_average_fraction=moving_average_fraction,
        use_global_stats=bool(use_global_stats),
    )
    conf = LayerConf(
        name=name or auto_name("batch_norm"),
        type="batch_norm",
        size=input.size,
        inputs=(input.name,),
        act=act_name(act),
        bias=False,
        attrs=attrs,
    )
    return LayerOutput(conf, [input])


batch_norm_layer = batch_norm


def maxout(
    input: LayerOutput,
    groups: int,
    num_channels: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    out_c = in_c // groups
    conf = LayerConf(
        name=name or auto_name("maxout"),
        type="maxout",
        size=in_h * in_w * out_c,
        inputs=(input.name,),
        bias=False,
        attrs={
            "in_c": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "groups": groups,
            "channels": out_c,
            "out_h": in_h,
            "out_w": in_w,
        },
    )
    return LayerOutput(conf, [input])


maxout_layer = maxout


def spp(
    input: LayerOutput,
    pyramid_height: int = 3,
    pool_type=None,
    num_channels: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    size = in_c * sum((2**l) * (2**l) for l in range(pyramid_height))
    conf = LayerConf(
        name=name or auto_name("spp"),
        type="spp",
        size=size,
        inputs=(input.name,),
        bias=False,
        attrs={
            "in_c": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "pyramid_height": pyramid_height,
            "pool_type": pool_name(pool_type),
        },
    )
    return LayerOutput(conf, [input])


spp_layer = spp


def bilinear_interp(
    input: LayerOutput,
    out_size_x: int,
    out_size_y: int,
    num_channels: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    conf = LayerConf(
        name=name or auto_name("bilinear_interp"),
        type="bilinear_interp",
        size=out_size_x * out_size_y * in_c,
        inputs=(input.name,),
        bias=False,
        attrs={
            "in_c": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "out_h": out_size_y,
            "out_w": out_size_x,
            "channels": in_c,
        },
    )
    return LayerOutput(conf, [input])


bilinear_interp_layer = bilinear_interp


def img_pad(
    input: LayerOutput,
    pad_c=(0, 0),
    pad_h=(0, 0),
    pad_w=(0, 0),
    num_channels: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    out_c = in_c + sum(pad_c)
    out_h = in_h + sum(pad_h)
    out_w = in_w + sum(pad_w)
    conf = LayerConf(
        name=name or auto_name("pad"),
        type="pad",
        size=out_c * out_h * out_w,
        inputs=(input.name,),
        bias=False,
        attrs={
            "in_c": in_c,
            "in_h": in_h,
            "in_w": in_w,
            "pad_c": tuple(pad_c),
            "pad_h_pair": tuple(pad_h),
            "pad_w_pair": tuple(pad_w),
            "channels": out_c,
            "out_h": out_h,
            "out_w": out_w,
        },
    )
    return LayerOutput(conf, [input])


pad_layer = img_pad


def crop(
    input: Inputish,
    offset: Optional[Sequence[int]] = None,
    axis: int = 2,
    shape: Optional[Sequence[int]] = None,
    name: Optional[str] = None,
    layer_attr=None,
) -> LayerOutput:
    """reference crop_layer (layers.py:6044) → CropLayer.cpp: crop the image
    input to `shape` — or to a second reference input's geometry — starting
    at `axis` (1=C,H,W; 2=H,W; 3=W), at the given offsets (default 0)."""
    ins = _as_list(input)
    x = ins[0]
    in_c, in_h, in_w = _img_attrs(x, None)
    if len(ins) == 2:
        rc, rh, rw = _img_attrs(ins[1], None)
        target = (rc, rh, rw)
    else:
        assert shape is not None, "crop_layer needs a reference input or shape"
        s = list(shape)
        # shape names the cropped trailing dims starting at `axis` (NCHW)
        tail = {1: 3, 2: 2, 3: 1}[axis]
        assert len(s) >= tail, f"crop shape {shape} too short for axis {axis}"
        s = s[-tail:]
        target = (in_c, in_h, in_w)
        target = tuple(
            s[i - (3 - tail)] if i >= 3 - tail else target[i] for i in range(3)
        )
    out_c = target[0] if axis <= 1 else in_c
    out_h = target[1] if axis <= 2 else in_h
    out_w = target[2]
    # offset entries align to the cropped axes starting at `axis` (reference
    # crop_layer: axis=2, offset=[h, w]) — pad MISSING LEADING axes with 0
    offs = list(offset) if offset is not None else []
    offs = [0] * (3 - len(offs)) + offs
    conf = LayerConf(
        name=name or auto_name("crop"),
        type="crop",
        size=out_c * out_h * out_w,
        inputs=tuple(i.name for i in ins),
        bias=False,
        attrs={
            "in_c": in_c, "in_h": in_h, "in_w": in_w,
            "out_c": out_c, "out_h": out_h, "out_w": out_w,
            "offset_c": offs[0] if axis <= 1 else 0,
            "offset_h": offs[1] if axis <= 2 else 0,
            "offset_w": offs[2],
            "channels": out_c,
        },
    )
    return LayerOutput(conf, ins)


crop_layer = crop


# ---------------------------------------------------------------------------
# simple math layers
# ---------------------------------------------------------------------------


def _unary(type_: str, input: LayerOutput, size=None, name=None, **attrs) -> LayerOutput:
    if size is None and input.conf.attr("dynamic_size"):
        # width-preserving op over a runtime-batch-wide input (e.g. stacked
        # on trans(height=None)): the dynamic-width hazard propagates
        attrs.setdefault("dynamic_size", True)
    conf = LayerConf(
        name=name or auto_name(type_),
        type=type_,
        size=size if size is not None else input.size,
        inputs=(input.name,),
        bias=False,
        attrs=attrs,
    )
    return LayerOutput(conf, [input])


def slope_intercept(input, slope=1.0, intercept=0.0, name=None):
    return _unary("slope_intercept", input, name=name, slope=slope, intercept=intercept)


slope_intercept_layer = slope_intercept


def scaling(weight: LayerOutput, input: LayerOutput, name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("scaling"),
        type="scaling",
        size=input.size,
        inputs=(weight.name, input.name),
        bias=False,
    )
    return LayerOutput(conf, [weight, input])


scaling_layer = scaling


def interpolation(
    weight: LayerOutput = None,
    input1: LayerOutput = None,
    input2: LayerOutput = None,
    input: Optional[Sequence[LayerOutput]] = None,
    name=None,
    layer_attr=None,
) -> LayerOutput:
    """y = w*x1 + (1-w)*x2.  Accepts either the positional (weight, x1, x2)
    form or the reference interpolation_layer(input=[x1, x2], weight=w)."""
    if input is not None:
        input1, input2 = input
    conf = LayerConf(
        name=name or auto_name("interpolation"),
        type="interpolation",
        size=input1.size,
        inputs=(weight.name, input1.name, input2.name),
        bias=False,
    )
    return LayerOutput(conf, [weight, input1, input2])


interpolation_layer = interpolation


def sum_to_one_norm(input, name=None):
    return _unary("sum_to_one_norm", input, name=name)


sum_to_one_norm_layer = sum_to_one_norm


def row_l2_norm(input, name=None):
    return _unary("row_l2_norm", input, name=name)


row_l2_norm_layer = row_l2_norm


def clip(input, min=-1.0, max=1.0, name=None):
    return _unary("clip", input, name=name, min=min, max=max)


clip_layer = clip


def maxid(input, name=None):
    return _unary("maxid", input, size=1, name=name)


maxid_layer = maxid


def trans(input, height: Optional[int] = None, name=None, layer_attr=None):
    """height=None: whole-minibatch transpose (reference trans_layer →
    TransLayer.cpp); height=H: per-sample [H, W] feature-block transpose
    (the rotate/trans feature-map variant).

    For height=None the output feature width is the RUNTIME batch size; the
    static conf size stays input.size for config parity with the reference
    parser (TransLayer, config_parser.py:2122-2129 keeps input size), but
    the conf is tagged dynamic_size so size-consuming consumers (fc) warn
    that their static weight shape only matches batch == input.size."""
    dyn = {"dynamic_size": True} if height is None else {}
    return _unary("trans", input, name=name, height=height, **dyn)


trans_layer = trans


def repeat(input, num_repeats: int, as_row_vector: bool = True, act=None,
           name=None, layer_attr=None):
    """reference repeat_layer (layers.py:1778): tile the feature vector
    num_repeats times (row-vector order) or repeat each element
    (column-vector order)."""
    ins = _as_list(input)
    conf = LayerConf(
        name=name or auto_name("repeat"),
        type="repeat",
        size=ins[0].size * num_repeats,
        inputs=(ins[0].name,),
        act=act_name(act),
        bias=False,
        attrs={"num_repeats": num_repeats, "as_row_vector": as_row_vector},
    )
    return LayerOutput(conf, ins)


repeat_layer = repeat


def featmap_expand(input, num_filters: int, as_row_vector: bool = True,
                   name=None):
    """reference featmap_expand_layer (FeatureMapExpandLayer.cpp): tile a
    feature map across num_filters channels, row- or column-vector order."""
    ins = _as_list(input)
    conf = LayerConf(
        name=name or auto_name("featmap_expand"),
        type="featmap_expand",
        size=ins[0].size * num_filters,
        inputs=(ins[0].name,),
        act="identity",
        bias=False,
        attrs={"num_filters": num_filters, "as_row_vector": as_row_vector},
    )
    return LayerOutput(conf, ins)


featmap_expand_layer = featmap_expand


def resize(input, size: int, name=None):
    return _unary("resize", input, size=size, name=name)


resize_layer = resize


def multiplex(input: Sequence[LayerOutput], name=None) -> LayerOutput:
    ins = _as_list(input)
    conf = LayerConf(
        name=name or auto_name("multiplex"),
        type="multiplex",
        size=ins[1].size,
        inputs=tuple(i.name for i in ins),
        bias=False,
    )
    return LayerOutput(conf, ins)


multiplex_layer = multiplex


def dotmul_operator(a: LayerOutput, b: LayerOutput, scale: float = 1.0, name=None):
    conf = LayerConf(
        name=name or auto_name("dotmul"),
        type="dotmul",
        size=a.size,
        inputs=(a.name, b.name),
        bias=False,
        attrs={"scale": scale},
    )
    return LayerOutput(conf, [a, b])


def moe(
    input: LayerOutput,
    expert_hidden: int,
    num_experts: int,
    size: Optional[int] = None,
    capacity_factor: float = 1.25,
    act=None,
    bias_attr: Union[bool, ParamAttr] = True,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Mixture-of-experts FFN with top-1 capacity routing (layers/moe.py).
    ``layer_attr=ExtraAttr(shard_axis='model')`` shards the experts over the
    mesh model axis — EXPERT PARALLELISM, with XLA inserting the dispatch/
    combine all-to-all.  The router's load-balance term rides the aux output
    ``<name>@aux_loss`` (pick it up via get_output + sum_cost)."""
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("moe"),
        type="moe",
        size=size or input.size,
        inputs=(input.name,),
        bias=bool(bias_attr),
        drop_rate=drop,
        shard_axis=shard,
        attrs={
            "num_experts": num_experts,
            "expert_hidden": expert_hidden,
            "capacity_factor": capacity_factor,
            "active_type": act_name(act if act is not None else _act_mod.Relu()),
            **_param_attrs(param_attr),
        },
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


moe_layer = moe


def gated_unit(
    input: LayerOutput,
    size: int,
    act=None,
    name: Optional[str] = None,
    gate_attr=None,
    gate_param_attr: Optional[ParamAttr] = None,
    gate_bias_attr=True,
    inproj_attr=None,
    inproj_param_attr: Optional[ParamAttr] = None,
    inproj_bias_attr=True,
    layer_attr=None,
) -> LayerOutput:
    """reference gated_unit_layer (layers.py): GLU — proj(input) ⊙
    σ(gate(input)) (Dauphin et al.; the conv_seq_to_seq building block)."""
    proj = fc(
        input, size=size,
        act=act if act is not None else _act_mod.Identity(),
        bias_attr=inproj_bias_attr,
        param_attr=inproj_param_attr, layer_attr=inproj_attr,
        name=(name + "_input_proj") if name else None,
    )
    gate = fc(
        input, size=size, act=_act_mod.Sigmoid(), bias_attr=gate_bias_attr,
        param_attr=gate_param_attr, layer_attr=gate_attr,
        name=(name + "_gate") if name else None,
    )
    return dotmul_operator(a=proj, b=gate, name=name)


gated_unit_layer = gated_unit


def out_prod(input1: LayerOutput, input2: LayerOutput, name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("out_prod"),
        type="out_prod",
        size=input1.size * input2.size,
        inputs=(input1.name, input2.name),
        bias=False,
    )
    return LayerOutput(conf, [input1, input2])


out_prod_layer = out_prod


def cos_sim(a: LayerOutput, b: LayerOutput, scale: float = 1.0, size: int = 1,
            name=None, layer_attr=None) -> LayerOutput:
    """size>1: b holds `size` concatenated vectors of a's width; one cosine
    per vector (reference cos_sim size param → CosSimLayer N similarities)."""
    if size > 1:
        assert b.size == a.size * size, (
            f"cos_sim size={size}: b.size {b.size} != a.size*{size}"
        )
    conf = LayerConf(
        name=name or auto_name("cos_sim"),
        type="cos",
        size=size,
        inputs=(a.name, b.name),
        bias=False,
        attrs={"scale": scale, "cos_n": size},
    )
    return LayerOutput(conf, [a, b])


def tensor(*args, **kwargs):
    """reference tensor_layer(a=..., b=..., size=...): bilinear
    y_k = a W_k b^T.  Accepts the (input1, input2, ...) positional form
    too."""
    if "a" in kwargs:
        kwargs["input1"] = kwargs.pop("a")
    if "b" in kwargs:
        kwargs["input2"] = kwargs.pop("b")
    kwargs.pop("layer_attr", None)
    return _tensor_impl(*args, **kwargs)


def _tensor_impl(
    input1: LayerOutput,
    input2: LayerOutput,
    size: int,
    act=None,
    bias_attr=True,
    name=None,
) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("tensor"),
        type="tensor",
        size=size,
        inputs=(input1.name, input2.name),
        act=act_name(act),
        bias=bool(bias_attr),
    )
    return LayerOutput(conf, [input1, input2])


tensor_layer = tensor


# ---------------------------------------------------------------------------
# cost layers
# ---------------------------------------------------------------------------


def _cost2(type_: str, input: LayerOutput, label: LayerOutput, name=None, **attrs):
    conf = LayerConf(
        name=name or auto_name(type_),
        type=type_,
        size=1,
        inputs=(input.name, label.name),
        bias=False,
        attrs=attrs,
    )
    return LayerOutput(conf, [input, label])


def _weighted(cost: LayerOutput, weight, name=None) -> LayerOutput:
    """Per-sample weighted cost (reference CostLayer weight input): the [B,1]
    weight slot scales the [B,1] per-sample cost — exactly the scaling layer."""
    if weight is None:
        return cost
    return scaling(weight, cost, name=name)


def classification_cost(
    input: LayerOutput, label: LayerOutput, weight=None, name=None, evaluator=None,
    layer_attr=None,
) -> LayerOutput:
    """reference classification_cost: softmax output + cross-entropy (the
    compiler fuses into log-softmax CE when the input's act is softmax)."""
    inner = _cost2(
        "cross_entropy", input, label,
        name=(name + "_unweighted") if (name and weight is not None) else name,
    )
    return _weighted(inner, weight, name=name)


def cross_entropy_cost(input, label, name=None):
    return _cost2("cross_entropy", input, label, name=name)


def cross_entropy_with_selfnorm_cost(input, label, softmax_selfnorm_alpha=0.1, name=None):
    return _cost2(
        "cross_entropy_with_selfnorm",
        input,
        label,
        name=name,
        softmax_selfnorm_alpha=softmax_selfnorm_alpha,
    )


def multi_binary_label_cross_entropy_cost(input, label, name=None):
    return _cost2("multi_binary_label_cross_entropy", input, label, name=name)


def soft_binary_class_cross_entropy_cost(input, label, name=None):
    return _cost2("soft_binary_class_cross_entropy", input, label, name=name)


def square_error_cost(input, label, weight=None, name=None, layer_attr=None):
    inner = _cost2(
        "square_error", input, label,
        name=(name + "_unweighted") if (name and weight is not None) else name,
    )
    return _weighted(inner, weight, name=name)


mse_cost = square_error_cost
regression_cost = square_error_cost


def smooth_l1_cost(input, label, name=None):
    return _cost2("smooth_l1", input, label, name=name)


def huber_regression_cost(input, label, delta=1.0, name=None):
    return _cost2("huber_regression", input, label, name=name, delta=delta)


def huber_classification_cost(input, label, name=None):
    return _cost2("huber_classification", input, label, name=name)


# reference-era name: huber_cost was the binary-classification huber loss
huber_cost = huber_classification_cost


def rank_cost(left: LayerOutput, right: LayerOutput, label: LayerOutput, name=None):
    conf = LayerConf(
        name=name or auto_name("rank_cost"),
        type="rank_cost",
        size=1,
        inputs=(left.name, right.name, label.name),
        bias=False,
    )
    return LayerOutput(conf, [left, right, label])


def sum_cost(input: LayerOutput, name=None):
    return _unary("sum_cost", input, size=1, name=name)


# v1 cost-layer aliases without the _cost suffix (reference layers.py __all__)
cross_entropy = cross_entropy_cost
cross_entropy_with_selfnorm = cross_entropy_with_selfnorm_cost
multi_binary_label_cross_entropy = multi_binary_label_cross_entropy_cost
soft_binary_class_cross_entropy = soft_binary_class_cross_entropy_cost
square_error = square_error_cost
mse_cost = square_error_cost
regression_cost = square_error_cost
smooth_l1 = smooth_l1_cost


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------


def pooling(
    input: LayerOutput,
    pooling_type=None,
    agg_level: int = AggregateLevel.TO_NO_SEQUENCE,
    stride: int = -1,
    bias_attr=False,
    name: Optional[str] = None,
    layer_attr=None,
) -> LayerOutput:
    """Pool a sequence over time (reference pooling_layer → SequencePoolLayer).
    With nested input, agg_level picks whether whole outer sequences
    (TO_NO_SEQUENCE) or individual subsequences (TO_SEQUENCE) collapse.
    stride>0 pools fixed windows of `stride` steps, emitting a shorter
    sequence."""
    if stride > 0:
        assert agg_level == AggregateLevel.TO_NO_SEQUENCE
    conf = LayerConf(
        name=name or auto_name("seqpool"),
        type="seqpool",
        size=input.size,
        inputs=(input.name,),
        bias=False,
        attrs={
            "pool_type": pool_name(pooling_type),
            "agg_level": agg_level,
            "stride": stride,
            "output_max_index": bool(
                getattr(pooling_type, "output_max_index", False)
            ),
        },
    )
    return LayerOutput(conf, [input])


pooling_layer = pooling


def last_seq(
    input: LayerOutput,
    agg_level: int = AggregateLevel.TO_NO_SEQUENCE,
    stride: int = -1,
    name: Optional[str] = None,
    layer_attr=None,
) -> LayerOutput:
    return _unary(
        "seqlastins", input, name=name, select_first=False,
        agg_level=agg_level, stride=stride,
    )


def first_seq(
    input: LayerOutput,
    agg_level: int = AggregateLevel.TO_NO_SEQUENCE,
    stride: int = -1,
    name: Optional[str] = None,
    layer_attr=None,
) -> LayerOutput:
    return _unary(
        "seqlastins", input, name=name, select_first=True,
        agg_level=agg_level, stride=stride,
    )


def expand(
    input: LayerOutput,
    expand_as: LayerOutput,
    expand_level: int = ExpandLevel.FROM_NO_SEQUENCE,
    name: Optional[str] = None,
) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("expand"),
        type="expand",
        size=input.size,
        inputs=(input.name, expand_as.name),
        bias=False,
        attrs={"expand_level": expand_level},
    )
    return LayerOutput(conf, [input, expand_as])


expand_layer = expand


def seq_reshape(input: LayerOutput, reshape_size: int, name=None) -> LayerOutput:
    return _unary("seqreshape", input, size=reshape_size, name=name)


seq_reshape_layer = seq_reshape


def seq_concat(a: LayerOutput, b: LayerOutput, name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("seqconcat"),
        type="seqconcat",
        size=a.size,
        inputs=(a.name, b.name),
        bias=False,
    )
    return LayerOutput(conf, [a, b])


seq_concat_layer = seq_concat


def lstmemory(
    input: LayerOutput,
    size: Optional[int] = None,
    reverse: bool = False,
    act=None,
    gate_act=None,
    state_act=None,
    bias_attr=True,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference lstmemory (layers.py): input must be pre-projected to 4×size
    (typically by an fc/mixed layer)."""
    size = size or input.size // 4
    assert input.size == 4 * size, (
        f"lstmemory input size {input.size} must be 4*size ({4 * size})"
    )
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("lstmemory"),
        type="lstmemory",
        size=size,
        inputs=(input.name,),
        bias=bool(bias_attr),
        drop_rate=drop,
        shard_axis=shard,
        attrs={
            "reverse": reverse,
            "active_type": act_name(act if act is not None else _act_mod.Tanh()),
            "gate_act": act_name(gate_act if gate_act is not None else _act_mod.Sigmoid()),
            "state_act": act_name(state_act if state_act is not None else _act_mod.Tanh()),
            **_param_attrs(param_attr),
        },
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


def grumemory(
    input: LayerOutput,
    size: Optional[int] = None,
    reverse: bool = False,
    act=None,
    gate_act=None,
    bias_attr=True,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference grumemory: input pre-projected to 3×size."""
    size = size or input.size // 3
    assert input.size == 3 * size
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("gru"),
        type="gru",
        size=size,
        inputs=(input.name,),
        bias=bool(bias_attr),
        drop_rate=drop,
        shard_axis=shard,
        attrs={
            "reverse": reverse,
            "active_type": act_name(act if act is not None else _act_mod.Tanh()),
            "gate_act": act_name(gate_act if gate_act is not None else _act_mod.Sigmoid()),
            **_param_attrs(param_attr),
        },
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


def recurrent(
    input: LayerOutput,
    act=None,
    reverse: bool = False,
    bias_attr=True,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    drop, shard = _extra(layer_attr)
    # per-key global names: the reference names the recurrent WEIGHT via
    # Input(parameter_name=...) and the bias via Bias(parameter_name=...)
    # separately (e.g. the LTR fixtures tie all slots' recurrences to one
    # "rnn1.w0"/"rnn1.bias"), so w_h and b share under their own names
    pnames = {}
    pn = _param_name(param_attr)
    if pn:
        pnames["w_h"] = pn
    if isinstance(bias_attr, ParamAttr) and bias_attr.name:
        pnames["b"] = bias_attr.name
    conf = LayerConf(
        name=name or auto_name("recurrent"),
        type="recurrent",
        size=input.size,
        inputs=(input.name,),
        act=act_name(act if act is not None else _act_mod.Tanh()),
        bias=bool(bias_attr),
        drop_rate=drop,
        shard_axis=shard,
        attrs={
            "reverse": reverse,
            "param_std": _param_std(param_attr),
            "prune_sparsity": _prune_ratio(param_attr),
            **({"param_names": pnames} if pnames else {}),
        },
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


recurrent_layer = recurrent


def context_projection(
    input: LayerOutput,
    context_len: int,
    context_start: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference context_projection (config_parser.py ContextProjection):
    default start centers the window."""
    start = context_start if context_start is not None else -(context_len // 2)
    conf = LayerConf(
        name=name or auto_name("context_projection"),
        type="context_projection",
        size=input.size * context_len,
        inputs=(input.name,),
        bias=False,
        attrs={"context_len": context_len, "context_start": start},
    )
    return LayerOutput(conf, [input])


def row_conv(
    input: LayerOutput, context_len: int, act=None, name: Optional[str] = None
) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("row_conv"),
        type="row_conv",
        size=input.size,
        inputs=(input.name,),
        act=act_name(act),
        bias=False,
        attrs={"context_len": context_len},
    )
    return LayerOutput(conf, [input])


row_conv_layer = row_conv


def conv_shift(a: LayerOutput, b: LayerOutput, name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("conv_shift"),
        type="conv_shift",
        size=a.size,
        inputs=(a.name, b.name),
        bias=False,
    )
    return LayerOutput(conf, [a, b])


conv_shift_layer = conv_shift


def _step_param_names(param_attr, bias_attr, weight_keys) -> dict:
    """param_names map for step cells: the single reference param name ties
    every recurrent weight key; a named bias attr ties the bias."""
    pnames = {}
    pn = _param_name(param_attr)
    if pn:
        for k in weight_keys:
            pnames[k] = f"{pn}#{k}"
    if isinstance(bias_attr, ParamAttr) and bias_attr.name:
        pnames["b"] = bias_attr.name
    return pnames


def gru_step(
    input: LayerOutput,
    output_mem: LayerOutput,
    size: Optional[int] = None,
    act=None,
    gate_act=None,
    bias_attr=True,
    param_attr: Optional[ParamAttr] = None,
    layer_attr=None,
    name: Optional[str] = None,
    naive: bool = False,
) -> LayerOutput:
    """One GRU step (reference gru_step_layer): input pre-projected to 3H,
    output_mem = previous state (usually a memory).  naive=True is the
    reference gru_step_naive_layer — the SAME recurrence (GruCompute) built
    from three separate projections; its one behavioral difference is that a
    NAMED param_attr ties all three recurrent blocks to ONE H×H matrix
    (each full_matrix_projection receives the same param name), which maps
    to tied_weights here."""
    size = size or output_mem.size
    assert input.size == 3 * size
    tied = naive and _param_name(param_attr) is not None
    if tied:
        pnames = _step_param_names(param_attr, bias_attr, ("w",))
        pnames["w"] = _param_name(param_attr)
    else:
        pnames = _step_param_names(param_attr, bias_attr, ("w_h", "w_c"))
    conf = LayerConf(
        name=name or auto_name("gru_step"),
        type="gru_step",
        size=size,
        inputs=(input.name, output_mem.name),
        bias=bool(bias_attr),
        attrs={
            "active_type": act_name(act if act is not None else _act_mod.Tanh()),
            "gate_act": act_name(gate_act if gate_act is not None else _act_mod.Sigmoid()),
            "param_std": _param_std(param_attr),
            **({"naive": True} if naive else {}),
            **({"tied_weights": True} if tied else {}),
            **({"param_names": pnames} if pnames else {}),
        },
    )
    return LayerOutput(conf, [input, output_mem])


gru_step_layer = gru_step


def lstm_step(
    input: LayerOutput,
    output_mem: LayerOutput,
    state_mem: LayerOutput,
    size: Optional[int] = None,
    act=None,
    gate_act=None,
    state_act=None,
    bias_attr=True,
    recurrent_weight: bool = True,
    layer_attr=None,
    name: Optional[str] = None,
) -> LayerOutput:
    """One LSTM step (reference lstm_step_layer): cell state is exposed as
    `<name>@cell` for a second memory link.  recurrent_weight=False matches
    the reference exactly (no W_h inside the step — lstmemory_unit feeds the
    recurrence through a mixed projection instead); True keeps the fused
    convenience form."""
    size = size or output_mem.size
    assert input.size == 4 * size
    pnames = _step_param_names(None, bias_attr, ())
    conf = LayerConf(
        name=name or auto_name("lstm_step"),
        type="lstm_step",
        size=size,
        inputs=(input.name, output_mem.name, state_mem.name),
        bias=bool(bias_attr),
        attrs={
            "active_type": act_name(act if act is not None else _act_mod.Tanh()),
            "gate_act": act_name(gate_act if gate_act is not None else _act_mod.Sigmoid()),
            "state_act": act_name(state_act if state_act is not None else _act_mod.Tanh()),
            "recurrent_weight": recurrent_weight,
            **({"param_names": pnames} if pnames else {}),
        },
    )
    return LayerOutput(conf, [input, output_mem, state_mem])


lstm_step_layer = lstm_step


def sampling_id(input: LayerOutput, name=None) -> LayerOutput:
    return _unary("sampling_id", input, size=1, name=name)


sampling_id_layer = sampling_id


def eos(input: LayerOutput, eos_id: int, name=None) -> LayerOutput:
    return _unary("eos_id", input, size=1, name=name, eos_id=eos_id)


eos_layer = eos


# ---------------------------------------------------------------------------
# misc inventory layers (layers/misc.py impls)
# ---------------------------------------------------------------------------


def prelu(input: LayerOutput, partial_sum: int = 1, name=None) -> LayerOutput:
    return _unary("prelu", input, name=name, partial_sum=partial_sum)


prelu_layer = prelu


def power(input: LayerOutput, weight: LayerOutput, name=None) -> LayerOutput:
    """reference power_layer: y = input ^ weight (weight [B,1])."""
    conf = LayerConf(
        name=name or auto_name("power"),
        type="power",
        size=input.size,
        inputs=(weight.name, input.name),
        bias=False,
    )
    return LayerOutput(conf, [weight, input])


power_layer = power


def data_norm(input: LayerOutput, strategy: str = "z-score", name=None) -> LayerOutput:
    return _unary("data_norm", input, name=name, strategy=strategy)


def block_expand(
    input: LayerOutput,
    block_x: int,
    block_y: int,
    stride_x: int = 1,
    stride_y: int = 1,
    padding_x: int = 0,
    padding_y: int = 0,
    num_channels: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference block_expand_layer (BlockExpandLayer.cpp): im2col into a
    block sequence."""
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    conf = LayerConf(
        name=name or auto_name("block_expand"),
        type="block_expand",
        size=in_c * block_x * block_y,
        inputs=(input.name,),
        bias=False,
        attrs={
            "in_h": in_h, "in_w": in_w, "in_c": in_c,
            "block_x": block_x, "block_y": block_y,
            "stride_x": stride_x, "stride_y": stride_y,
            "padding_x": padding_x, "padding_y": padding_y,
        },
    )
    return LayerOutput(conf, [input])


block_expand_layer = block_expand


def rotate(input: LayerOutput, height: Optional[int] = None,
           width: Optional[int] = None, name=None) -> LayerOutput:
    a = _img_passthrough(input)
    in_h = height or a.get("in_h")
    in_w = width or a.get("in_w")
    in_c = a.get("in_c", 1)
    conf = LayerConf(
        name=name or auto_name("rotate"),
        type="rotate",
        size=input.size,
        inputs=(input.name,),
        bias=False,
        attrs={"in_h": in_h, "in_w": in_w, "in_c": in_c,
               "out_h": in_w, "out_w": in_h, "channels": in_c},
    )
    return LayerOutput(conf, [input])


rotate_layer = rotate


def sub_seq(input: LayerOutput, offsets: LayerOutput, sizes: LayerOutput,
            name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("sub_seq"),
        type="sub_seq",
        size=input.size,
        inputs=(input.name, offsets.name, sizes.name),
        bias=False,
    )
    return LayerOutput(conf, [input, offsets, sizes])


sub_seq_layer = sub_seq


def linear_comb(weights: LayerOutput, vectors: LayerOutput,
                size: Optional[int] = None, name=None,
                layer_attr=None) -> LayerOutput:
    """reference linear_comb_layer / convex_comb_layer: vectors holds W
    groups of `size` features; weights [B, W] combines them.  size defaults
    to vectors.size // weights.size (the reference's implicit sizing)."""
    if size is None:
        assert vectors.size % weights.size == 0, (
            f"linear_comb: vectors.size {vectors.size} not a multiple of "
            f"weights.size {weights.size}"
        )
        size = vectors.size // weights.size
    conf = LayerConf(
        name=name or auto_name("linear_comb"),
        type="linear_comb",
        size=size,
        inputs=(weights.name, vectors.name),
        bias=False,
    )
    return LayerOutput(conf, [weights, vectors])


convex_comb = linear_comb
convex_comb_layer = linear_comb
linear_comb_layer = linear_comb


def cos_sim_vec_mat(vec: LayerOutput, mat: LayerOutput, size: int,
                    scale: float = 1.0, name=None) -> LayerOutput:
    """reference cos_vm (CosSimVecMatLayer.cpp)."""
    conf = LayerConf(
        name=name or auto_name("cos_vm"),
        type="cos_vm",
        size=size,
        inputs=(vec.name, mat.name),
        bias=False,
        attrs={"scale": scale},
    )
    return LayerOutput(conf, [vec, mat])


def print_layer(input: LayerOutput, format: str = "{name}: {val}", name=None) -> LayerOutput:
    return _unary("print", input, name=name, format=format)


def scale_shift(input: LayerOutput, bias_attr: Union[bool, ParamAttr] = True,
                name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("scale_shift"),
        type="scale_shift",
        size=input.size,
        inputs=(input.name,),
        bias=bool(bias_attr),
    )
    return LayerOutput(conf, [input])


scale_shift_layer = scale_shift


def kmax_seq_score(input: LayerOutput, beam_size: int = 1, name=None) -> LayerOutput:
    conf = LayerConf(
        name=name or auto_name("kmax_seq_score"),
        type="kmax_seq_score",
        size=beam_size,
        inputs=(input.name,),
        bias=False,
        attrs={"beam_size": beam_size},
    )
    return LayerOutput(conf, [input])


# ---------------------------------------------------------------------------
# large-vocab output layers: nce / hsigmoid / selective_fc / lambda_cost
# (reference layers.py nce_layer, hsigmoid, selective_fc_layer, lambda_cost)
# ---------------------------------------------------------------------------


def nce(
    input: Inputish,
    label: LayerOutput,
    num_classes: Optional[int] = None,
    num_neg_samples: int = 10,
    noise_dist: Optional[Sequence[float]] = None,
    neg_distribution: Optional[Sequence[float]] = None,  # reference name
    bias_attr: Union[bool, ParamAttr] = True,
    param_attr: Optional[ParamAttr] = None,
    weight: Optional[LayerOutput] = None,
    name: Optional[str] = None,
    layer_attr=None,
) -> LayerOutput:
    if noise_dist is None:
        noise_dist = neg_distribution
    feats = _as_list(input)
    c = num_classes or label.size
    conf = LayerConf(
        name=(
            (name + "_unweighted") if (name and weight is not None) else name
        ) or auto_name("nce"),
        type="nce",
        size=1,
        inputs=tuple(f.name for f in feats) + (label.name,),
        bias=bool(bias_attr),
        attrs={
            "num_classes": c,
            "num_neg_samples": num_neg_samples,
            "num_feat_inputs": len(feats),
            "noise_dist": tuple(noise_dist) if noise_dist is not None else None,
        },
    )
    return _weighted(LayerOutput(conf, feats + [label]), weight, name=name)


nce_layer = nce


def hsigmoid(
    input: Inputish,
    label: LayerOutput,
    num_classes: Optional[int] = None,
    bias_attr: Union[bool, ParamAttr] = True,
    param_attr: Optional[ParamAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    feats = _as_list(input)
    c = num_classes or label.size
    conf = LayerConf(
        name=name or auto_name("hsigmoid"),
        type="hsigmoid",
        size=1,
        inputs=tuple(f.name for f in feats) + (label.name,),
        bias=bool(bias_attr),
        attrs={"num_classes": c},
    )
    return LayerOutput(conf, feats + [label])


def selective_fc(
    input: Inputish,
    select: Optional[LayerOutput],
    size: int,
    act=None,
    bias_attr: Union[bool, ParamAttr] = True,
    param_attr: Optional[ParamAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    feats = _as_list(input)
    parents = feats + ([select] if select is not None else [])
    conf = LayerConf(
        name=name or auto_name("selective_fc"),
        type="selective_fc",
        size=size,
        inputs=tuple(p.name for p in parents),
        act=act_name(act),
        bias=bool(bias_attr),
        attrs={"has_selection": select is not None, **_param_attrs(param_attr)},
    )
    return LayerOutput(conf, parents)


selective_fc_layer = selective_fc


def lambda_cost(
    input: LayerOutput,
    score: LayerOutput,
    NDCG_num: int = 5,
    max_sort_size: int = -1,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference lambda_cost (LambdaCost.cpp): `input` is the model score
    sequence, `score` the gold relevance sequence.  max_sort_size is accepted
    for API parity; the TPU version always ranks the full (padded) list."""
    conf = LayerConf(
        name=name or auto_name("lambda_cost"),
        type="lambda_cost",
        size=1,
        inputs=(input.name, score.name),
        bias=False,
        attrs={"ndcg_num": NDCG_num},
    )
    return LayerOutput(conf, [input, score])


# ---------------------------------------------------------------------------
# structured prediction: crf / crf_decoding / ctc / warp_ctc
# (reference layers.py crf_layer, crf_decoding_layer, ctc_layer, warp_ctc_layer)
# ---------------------------------------------------------------------------


def crf(
    input: LayerOutput,
    label: LayerOutput,
    size: Optional[int] = None,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Linear-chain CRF cost (reference crf_layer → CRFLayer.cpp)."""
    n = size or input.size
    conf = LayerConf(
        name=name or auto_name("crf"),
        type="crf",
        size=1,
        inputs=(input.name, label.name),
        bias=False,
        attrs={"num_classes": n, **_param_attrs(param_attr)},
    )
    return LayerOutput(conf, [input, label])


crf_layer = crf


def crf_decoding(
    input: LayerOutput,
    size: Optional[int] = None,
    label: Optional[LayerOutput] = None,
    param_attr: Optional[ParamAttr] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Viterbi decoding (reference crf_decoding_layer → CRFDecodingLayer.cpp);
    with `label`, emits per-position mismatch indicators."""
    n = size or input.size
    parents = [input] + ([label] if label is not None else [])
    conf = LayerConf(
        name=name or auto_name("crf_decoding"),
        type="crf_decoding",
        size=n,
        inputs=tuple(p.name for p in parents),
        bias=False,
        attrs={"num_classes": n, **_param_attrs(param_attr)},
    )
    return LayerOutput(conf, parents)


crf_decoding_layer = crf_decoding


def ctc(
    input: LayerOutput,
    label: LayerOutput,
    size: Optional[int] = None,
    blank: Optional[int] = None,
    norm_by_times: bool = False,
    name: Optional[str] = None,
) -> LayerOutput:
    """CTC cost (reference ctc_layer → CTCLayer.cpp/LinearChainCTC.cpp).
    `size` = num_classes + 1 (incl. blank); blank defaults to size-1."""
    n = size or input.size
    conf = LayerConf(
        name=name or auto_name("ctc"),
        type="ctc",
        size=1,
        inputs=(input.name, label.name),
        bias=False,
        attrs={
            "blank": blank if blank is not None else n - 1,
            "norm_by_times": norm_by_times,
            "_num_classes": n,
        },
    )
    return LayerOutput(conf, [input, label])


ctc_layer = ctc


def warp_ctc(
    input: LayerOutput,
    label: LayerOutput,
    size: Optional[int] = None,
    blank: int = 0,
    norm_by_times: bool = False,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference warp_ctc_layer (WarpCTCLayer.cpp): same loss, blank=0
    convention.  On TPU both lower to the same scan DP."""
    return ctc(input, label, size=size, blank=blank,
               norm_by_times=norm_by_times, name=name or auto_name("warp_ctc"))


warp_ctc_layer = warp_ctc


# ---------------------------------------------------------------------------
# mixed layer + projections (reference: trainer_config_helpers mixed_layer +
# *_projection functions, config_parser.py:487-858; MixedLayer.cpp)
# ---------------------------------------------------------------------------


class Projection:
    """Spec for one term of a mixed layer.  Unlike a LayerOutput this is not
    itself a graph node — the enclosing mixed layer owns the parameters (the
    reference's Projection objects likewise live inside MixedLayer,
    Projection.h)."""

    def __init__(self, kind: str, input: LayerOutput, **attrs):
        self.kind = kind
        self.input = input
        self.attrs = attrs


def full_matrix_projection(
    input: LayerOutput, size: int = 0, param_attr: Optional[ParamAttr] = None
) -> Projection:
    return Projection(
        "full_matrix", input, size=size,
        param_std=_param_std(param_attr), param_name=_param_name(param_attr),
        **({"dynamic_width": True} if _dynamic_width(input) else {}),
    )


def trans_full_matrix_projection(
    input: LayerOutput, size: int = 0, param_attr: Optional[ParamAttr] = None
) -> Projection:
    return Projection(
        "trans_full_matrix", input, size=size,
        param_std=_param_std(param_attr), param_name=_param_name(param_attr),
        **({"dynamic_width": True} if _dynamic_width(input) else {}),
    )


def table_projection(
    input: LayerOutput, size: int = 0, param_attr: Optional[ParamAttr] = None
) -> Projection:
    return Projection(
        "table", input, size=size,
        param_std=_param_std(param_attr), param_name=_param_name(param_attr),
    )


def identity_projection(input: LayerOutput, offset: Optional[int] = None, size: int = 0) -> Projection:
    if offset is None:
        return Projection("identity", input)
    return Projection("identity_offset", input, offset=offset, size=size)


def slice_projection(input: LayerOutput, slices: Sequence[tuple]) -> Projection:
    return Projection("slice", input, slices=tuple(tuple(s) for s in slices))


def scaling_projection(input: LayerOutput) -> Projection:
    return Projection("scaling", input)


def dotmul_projection(
    input: LayerOutput, param_attr: Optional[ParamAttr] = None
) -> Projection:
    return Projection(
        "dotmul", input,
        param_std=_param_std(param_attr), param_name=_param_name(param_attr),
    )


def conv_projection(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    num_channels: Optional[int] = None,
    stride: int = 1,
    padding: int = 0,
    groups: int = 1,
    trans: bool = False,
    filter_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
    param_attr: Optional[ParamAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference conv_projection — here a bias-less conv layer the mixed
    layer consumes as an identity term (same math, reuses the conv impl)."""
    return img_conv(
        input,
        filter_size=filter_size,
        num_filters=num_filters,
        num_channels=num_channels,
        stride=stride,
        padding=padding,
        groups=groups,
        trans=trans,
        filter_size_y=filter_size_y,
        stride_y=stride_y,
        padding_y=padding_y,
        act=_act_mod.Identity(),
        bias_attr=False,
        param_attr=param_attr,
        name=name or auto_name("conv_proj"),
    )


def conv_operator(
    img: LayerOutput,
    filter: LayerOutput,
    filter_size: int,
    num_filters: int,
    num_channels: Optional[int] = None,
    stride: int = 1,
    padding: int = 0,
    filter_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
    trans: bool = False,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference conv_operator (ConvOperator.cpp): convolve the image input
    with per-sample filters produced by another layer.  trans=True runs the
    transposed (fractionally-strided) form."""
    in_c, in_h, in_w = _img_attrs(img, num_channels)
    fh, fw = filter_size_y or filter_size, filter_size
    sh, sw = stride_y or stride, stride
    ph = padding_y if padding_y is not None else padding
    pw = padding
    if trans:
        out_h = _conv.convt_output_size(in_h, fh, ph, sh)
        out_w = _conv.convt_output_size(in_w, fw, pw, sw)
    else:
        out_h = cnn_output_size(in_h, fh, ph, sh)
        out_w = cnn_output_size(in_w, fw, pw, sw)
    conf = LayerConf(
        name=name or auto_name("conv_op"),
        type="conv_op",
        size=num_filters * out_h * out_w,
        inputs=(img.name, filter.name),
        bias=False,
        attrs={
            "in_h": in_h, "in_w": in_w, "in_c": in_c,
            "filter_h": fh, "filter_w": fw,
            "channels": num_filters,
            "stride_h": sh, "stride_w": sw,
            "pad_h": ph, "pad_w": pw,
            "trans": trans,
            "out_h": out_h, "out_w": out_w, "out_c": num_filters,
        },
    )
    return LayerOutput(conf, [img, filter])


def mixed(
    size: int = 0,
    input: Union[Projection, LayerOutput, Sequence[Union[Projection, LayerOutput]], None] = None,
    name: Optional[str] = None,
    act=None,
    bias_attr: Union[bool, ParamAttr, None] = False,
    layer_attr: Optional[ExtraAttr] = None,
) -> LayerOutput:
    """reference mixed_layer (layers.py): sum of projections.  Plain
    LayerOutputs enter as identity terms (the standalone forms of
    context/conv projections and operators).

    With no input, returns the v1 CONTEXT-MANAGER builder::

        with mixed_layer() as m:
            m += full_matrix_projection(x)
        # m is the finished LayerOutput after the block
    """
    if input is None:
        return _MixedBuilder(
            size=size, name=name, act=act, bias_attr=bias_attr,
            layer_attr=layer_attr,
        )
    items = [input] if isinstance(input, (Projection, LayerOutput)) else list(input)
    parents: list = []
    specs: list = []
    for item in items:
        if isinstance(item, Projection):
            lo, kind, attrs = item.input, item.kind, dict(item.attrs)
        else:
            lo, kind, attrs = item, "identity", {}
        if lo.name not in [p.name for p in parents]:
            parents.append(lo)
        idx = [p.name for p in parents].index(lo.name)
        specs.append({"kind": kind, "in": idx, **attrs})
    if size == 0:
        inferred = [
            parents[s["in"]].size for s in specs
            if s["kind"] in ("identity", "dotmul", "scaling")
        ] + [s["size"] for s in specs if s.get("size")] + [
            sum(e - b for b, e in s["slices"])
            for s in specs if s["kind"] == "slice"
        ]
        assert inferred, "mixed() needs an explicit size"
        size = inferred[0]
    pnames = {
        f"p{j}_w": s["param_name"]
        for j, s in enumerate(specs)
        if s.get("param_name")
    }
    if isinstance(bias_attr, ParamAttr) and bias_attr.name:
        pnames["b"] = bias_attr.name
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("mixed"),
        type="mixed",
        size=size,
        inputs=tuple(p.name for p in parents),
        act=act_name(act),
        bias=bool(bias_attr),
        drop_rate=drop,
        shard_axis=shard,
        attrs={
            "projections": tuple(specs),
            **({"param_names": pnames} if pnames else {}),
        },
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, parents)


class _MixedBuilder(LayerOutput):
    """`with mixed_layer() as m: m += projection` support (reference
    layers.py MixedLayerType).  The object IS the resulting LayerOutput —
    its conf materializes when the with-block exits."""

    def __init__(self, **kw):
        self._kw = kw
        self._terms: list = []
        self.conf = None  # filled on __exit__
        self.parents = ()

    def __enter__(self):
        return self

    def __iadd__(self, term):
        self._terms.append(term)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        assert self._terms, "mixed_layer() block added no projections"
        built = mixed(input=self._terms, **self._kw)
        self.conf = built.conf
        self.parents = built.parents
        return False


mixed_layer = mixed


# ---------------------------------------------------------------------------
# attention family (Transformer building blocks — layers/attention.py)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# detection suite (SSD) — layers/detection.py
# ---------------------------------------------------------------------------


def priorbox(
    input: LayerOutput,
    image: LayerOutput,
    aspect_ratio: Sequence[float],
    variance: Sequence[float],
    min_size: Sequence[float],
    max_size: Sequence[float] = (),
    name: Optional[str] = None,
) -> LayerOutput:
    """reference priorbox_layer (layers.py:1049) → PriorBox.cpp.  Emits
    [B, P, 8] (prior corners + variances); P is fixed by the input feature
    map's geometry, so the priors fold to an XLA constant."""
    from paddle_tpu.ops.detection import make_priors, priors_per_cell

    fa = input.conf.attrs
    h = fa.get("out_h") or fa.get("in_h")
    w = fa.get("out_w") or fa.get("in_w")
    assert h and w, f"priorbox input {input.name} has no image geometry attrs"
    ia = image.conf.attrs
    img_h = ia.get("in_h") or ia.get("out_h")
    img_w = ia.get("in_w") or ia.get("out_w")
    assert img_h and img_w, (
        f"priorbox image {image.name} has no geometry — declare the data "
        f"layer with height=/width= (min_size is in image pixels)"
    )
    priors = make_priors(
        int(h), int(w), list(min_size), list(max_size), list(aspect_ratio),
        int(img_h), int(img_w),
    )
    k = priors_per_cell(len(min_size), len(max_size), aspect_ratio)
    conf = LayerConf(
        name=name or auto_name("priorbox"),
        type="priorbox",
        size=priors.shape[0] * 8,
        inputs=(input.name, image.name),
        bias=False,
        attrs={
            "_priors": priors,
            "variance": tuple(variance),
            "num_priors": int(priors.shape[0]),
            "priors_per_cell": int(k),
        },
    )
    return LayerOutput(conf, [input, image])


priorbox_layer = priorbox


def multibox_loss(
    input_loc,
    input_conf,
    priorbox: LayerOutput,
    label: LayerOutput,
    num_classes: int,
    overlap_threshold: float = 0.5,
    neg_pos_ratio: float = 3.0,
    neg_overlap: float = 0.5,
    background_id: int = 0,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference multibox_loss_layer (layers.py:1095) → MultiBoxLossLayer.cpp.
    `label` is a dense sequence slot of (label,xmin,ymin,xmax,ymax,difficult)
    rows per image."""
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    assert len(locs) == len(confs), "loc/conf input counts must match"
    parents = [priorbox, label] + locs + confs
    conf = LayerConf(
        name=name or auto_name("multibox_loss"),
        type="multibox_loss",
        size=1,
        inputs=tuple(p.name for p in parents),
        bias=False,
        attrs={
            "input_num": len(locs),
            "num_classes": num_classes,
            "overlap_threshold": overlap_threshold,
            "neg_pos_ratio": neg_pos_ratio,
            "neg_overlap": neg_overlap,
            "background_id": background_id,
        },
    )
    return LayerOutput(conf, parents)


multibox_loss_layer = multibox_loss


def detection_output(
    input_loc,
    input_conf,
    priorbox: LayerOutput,
    num_classes: int,
    nms_threshold: float = 0.45,
    nms_top_k: int = 400,
    keep_top_k: int = 200,
    confidence_threshold: float = 0.01,
    background_id: int = 0,
    name: Optional[str] = None,
) -> LayerOutput:
    """reference detection_output_layer (layers.py:1170) →
    DetectionOutputLayer.cpp.  Emits a fixed [B, keep_top_k, 6] block."""
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    assert len(locs) == len(confs)
    parents = [priorbox] + locs + confs
    conf = LayerConf(
        name=name or auto_name("detection_output"),
        type="detection_output",
        size=keep_top_k * 6,
        inputs=tuple(p.name for p in parents),
        bias=False,
        attrs={
            "input_num": len(locs),
            "num_classes": num_classes,
            "nms_threshold": nms_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "confidence_threshold": confidence_threshold,
            "background_id": background_id,
        },
    )
    return LayerOutput(conf, parents)


detection_output_layer = detection_output


def img_cmrnorm(
    input: LayerOutput,
    size: int,
    scale: float = 0.0128,
    power: float = 0.75,
    num_channels: Optional[int] = None,
    layer_attr: Optional[ExtraAttr] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Cross-map response normalization (reference img_cmrnorm_layer,
    layers.py:2706 — AlexNet LRN across `size` feature maps)."""
    in_c, in_h, in_w = _img_attrs(input, num_channels)
    drop, shard = _extra(layer_attr)
    conf = LayerConf(
        name=name or auto_name("crmnorm"),  # sic: the reference prefix
        type="norm",
        size=in_h * in_w * in_c,
        inputs=(input.name,),
        bias=False,
        drop_rate=drop,
        shard_axis=shard,
        attrs={
            "norm_size": size,
            "scale": scale,
            "power": power,
            "in_c": in_c, "in_h": in_h, "in_w": in_w,
            "channels": in_c, "out_h": in_h, "out_w": in_w,
        },
    )
    _set_error_clip(conf, layer_attr)
    return LayerOutput(conf, [input])


img_cmrnorm_layer = img_cmrnorm


def layer_norm(
    input: LayerOutput, epsilon: float = 1e-6, name: Optional[str] = None
) -> LayerOutput:
    return _unary("layer_norm", input, name=name, epsilon=epsilon)


def multi_head_attention(
    query: LayerOutput,
    key_value: Optional[LayerOutput] = None,
    size: Optional[int] = None,
    n_heads: int = 8,
    causal: bool = False,
    bias_attr: bool = True,
    seq_parallel_axis: Optional[str] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Multi-head attention; omit key_value for self-attention.  `causal`
    masks future positions (decoder self-attention).  `seq_parallel_axis`
    names a mesh axis to shard the sequence over — self-attention then runs
    as exact ring attention (long-context path, parallel/ring_attention)."""
    kv = key_value or query
    conf = LayerConf(
        name=name or auto_name("mha"),
        type="multi_head_attention",
        size=size or query.size,
        inputs=(query.name, kv.name),
        bias=bool(bias_attr),
        attrs={
            "n_heads": n_heads,
            "causal": causal,
            "seq_parallel_axis": seq_parallel_axis,
        },
    )
    return LayerOutput(conf, [query, kv])


def pos_encoding(
    input: LayerOutput, emb_scale: float = 1.0, name: Optional[str] = None
) -> LayerOutput:
    """Add sinusoidal position encodings (input is scaled by emb_scale
    first — pass sqrt(d_model) for the Transformer convention)."""
    return _unary("pos_encoding", input, name=name, emb_scale=emb_scale)


def mdlstmemory(
    input: LayerOutput,
    size: Optional[int] = None,
    reverse_h: bool = False,
    reverse_w: bool = False,
    act=None,
    gate_act=None,
    state_act=None,
    bias_attr: bool = True,
    name: Optional[str] = None,
) -> LayerOutput:
    """2D multi-dimensional LSTM (reference MDLstmLayer.cpp); input must be
    an image-shaped layer pre-projected to 5*size channels (i, f_row, f_col,
    o, g gates).  reverse_h/reverse_w flip the scan direction per axis —
    compose four of these for the full multi-directional net."""
    a = input.conf.attrs
    in_c = a.get("channels") or a.get("in_c")
    in_h = a.get("out_h") or a.get("in_h")
    in_w = a.get("out_w") or a.get("in_w")
    assert in_c and in_h and in_w, (
        f"mdlstmemory input {input.name} needs image geometry attrs"
    )
    size = size or int(in_c) // 5
    assert int(in_c) == 5 * size, (
        f"mdlstmemory input channels {in_c} must be 5*size ({5 * size})"
    )
    conf = LayerConf(
        name=name or auto_name("mdlstmemory"),
        type="mdlstmemory",
        # image-layer convention: size is the flattened extent; the hidden
        # width rides the channels attr (like img_conv)
        size=int(in_h) * int(in_w) * size,
        inputs=(input.name,),
        bias=bool(bias_attr),
        attrs={
            "in_h": int(in_h),
            "in_w": int(in_w),
            "in_c": int(in_c),
            "out_h": int(in_h),
            "out_w": int(in_w),
            "channels": size,
            "reverse_h": reverse_h,
            "reverse_w": reverse_w,
            "active_type": act_name(act if act is not None else _act_mod.Tanh()),
            "gate_act": act_name(gate_act if gate_act is not None else _act_mod.Sigmoid()),
            "state_act": act_name(state_act if state_act is not None else _act_mod.Tanh()),
        },
    )
    return LayerOutput(conf, [input])


mdlstmemory_layer = mdlstmemory


def get_output(
    input: LayerOutput,
    arg_name: str,
    size: Optional[int] = None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Select a named auxiliary output of a layer (reference
    get_output_layer → GetOutputLayer.cpp), e.g. the cell state of an
    lstm_step ('cell') or beam scores ('scores').  `size` overrides the
    declared width for aux outputs shaped unlike the main output."""
    if size is None:
        if input.conf.type == "beam_search" and arg_name == "scores":
            size = input.conf.attrs["beam_size"]
        else:
            size = input.size
    conf = LayerConf(
        name=name or auto_name("get_output"),
        type="get_output",
        size=size,
        inputs=(input.name,),
        bias=False,
        attrs={"arg_name": arg_name},
    )
    return LayerOutput(conf, [input])


get_output_layer = get_output


def agent(input: LayerOutput, size: Optional[int] = None, name: Optional[str] = None) -> LayerOutput:
    """Identity view of another layer (reference AgentLayer — cross-frame
    wiring that the recurrent_group scan absorbs here)."""
    conf = LayerConf(
        name=name or auto_name("agent"),
        type="agent",
        size=size or input.size,
        inputs=(input.name,),
        bias=False,
    )
    return LayerOutput(conf, [input])


agent_layer = agent


def scatter_agent(input: LayerOutput, ids: LayerOutput, name: Optional[str] = None) -> LayerOutput:
    """Select rows of `input` by the integer ids (reference
    ScatterAgentLayer: distributes source rows to beam/frame slots)."""
    conf = LayerConf(
        name=name or auto_name("scatter_agent"),
        type="scatter_agent",
        size=input.size,
        inputs=(input.name, ids.name),
        bias=False,
    )
    return LayerOutput(conf, [input, ids])


scatter_agent_layer = scatter_agent


def gather_agent(input: Sequence[LayerOutput], name: Optional[str] = None) -> LayerOutput:
    """Concatenate sequences along time (reference GatherAgentLayer:
    collects scattered pieces back into one sequence)."""
    ins = _as_list(input)
    conf = LayerConf(
        name=name or auto_name("gather_agent"),
        type="gather_agent",
        size=ins[0].size,
        inputs=tuple(i.name for i in ins),
        bias=False,
    )
    return LayerOutput(conf, ins)


gather_agent_layer = gather_agent


__all__ = [n for n in dir() if not n.startswith("_")]
