"""Image layers: conv, conv-transpose, pooling, batch_norm, maxout, pad, crop,
bilinear_interp, spp.

Reference counterparts: paddle/gserver/layers/{ExpandConvLayer,CudnnConvLayer,
PoolLayer,CudnnPoolLayer,BatchNormalizationLayer,MaxOutLayer,PadLayer,CropLayer,
BilinearInterpLayer,SpatialPyramidPoolLayer}.cpp and the hl_cnn.h HAL kernels.

TPU-native design: tensors flow NHWC (the layout XLA tiles best onto the MXU
for convolutions), whereas the reference flattens NCHW rows between layers.
A flat [B, C*H*W] input (e.g. straight from a data layer) is reshaped
CHW-order — matching the reference's memory layout — then transposed to NHWC
once; conv chains stay 4D throughout.  ``lax.conv_general_dilated`` handles
conv/conv-transpose, ``lax.reduce_window`` pooling; XLA fuses bias/activation.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer


def to_nhwc(x: jnp.ndarray, h: int, w: int, c: int) -> jnp.ndarray:
    """Accept [B, C*H*W] flat (CHW order) or already-4D NHWC."""
    if x.ndim == 4:
        return x
    b = x.shape[0]
    return x.reshape(b, c, h, w).transpose(0, 2, 3, 1)


# ---------------------------------------------------------------------------
# conv / convt
# ---------------------------------------------------------------------------


def conv_init(conf, in_confs, rng) -> Dict[str, Any]:
    a = conf.attrs
    kh, kw = a["filter_h"], a["filter_w"]
    cin, cout = a["in_c"], a["channels"]
    groups = a.get("groups", 1)
    if conf.type == "convt":
        # HWIO per group: axis 2 spans one group's input channels, axis 3
        # all output channels (grouped column blocks)
        shape = (kh, kw, cin // groups, cout)
        w = init.normal(rng, shape, init.default_std(kh * kw * cin // groups))
    else:
        shape = (kh, kw, cin // groups, cout)
        w = init.conv_normal(rng, shape)
    p = {"w": w}
    if conf.bias:
        p["b"] = init.zeros((cout,))
    return p


@register_layer("conv", init=conv_init)
def conv_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    out = lax.conv_general_dilated(
        x,
        params["w"],
        window_strides=(a.get("stride_h", 1), a.get("stride_w", 1)),
        padding=[
            (a.get("pad_h", 0), a.get("pad_h", 0)),
            (a.get("pad_w", 0), a.get("pad_w", 0)),
        ],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=a.get("groups", 1),
    )
    if "b" in params:
        out = out + params["b"]
    return SeqTensor(out, inputs[0].lengths)


def convt_output_size(in_size: int, filter_size: int, padding: int, stride: int) -> int:
    """Transposed-conv spatial output: (in-1)*s + k - 2p — the single
    source for every convt size computation (DSL + operators)."""
    return (in_size - 1) * stride + filter_size - 2 * padding


def conv_transpose_nhwc(x, w, *, strides, fh, fw, ph, pw, groups: int = 1):
    """Transposed conv as ONE lhs-dilated conv (the formulation XLA lowers
    natively, no kernel flip for HWIO weights): pad k-1-p per side on the
    stride-dilated input, VALID conv.  Shared by the convt layer and
    conv_operator(trans=True)."""
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=(1, 1),
        padding=[(fh - 1 - ph, fh - 1 - ph), (fw - 1 - pw, fw - 1 - pw)],
        lhs_dilation=strides,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


@register_layer("convt", init=conv_init)
def convt_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    out = conv_transpose_nhwc(
        x,
        params["w"],
        strides=(a.get("stride_h", 1), a.get("stride_w", 1)),
        fh=a["filter_h"], fw=a["filter_w"],
        ph=a.get("pad_h", 0), pw=a.get("pad_w", 0),
        groups=a.get("groups", 1),
    )
    if "b" in params:
        out = out + params["b"]
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# pool (max / avg), global variants
# ---------------------------------------------------------------------------


@register_layer("pool")
def pool_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    kh, kw = a["filter_h"], a["filter_w"]
    sh, sw = a.get("stride_h", 1), a.get("stride_w", 1)
    ph, pw = a.get("pad_h", 0), a.get("pad_w", 0)
    kind = a.get("pool_type", "max")
    # The DSL computes output sizes with v1's ceil mode (cnn_output_size);
    # reduce_window floors, so pad the high side to make them agree.
    out_h, out_w = a["out_h"], a["out_w"]
    extra_h = max((out_h - 1) * sh + kh - x.shape[1] - 2 * ph, 0)
    extra_w = max((out_w - 1) * sw + kw - x.shape[2] - 2 * pw, 0)
    window = (1, kh, kw, 1)
    strides = (1, sh, sw, 1)
    pads = ((0, 0), (ph, ph + extra_h), (pw, pw + extra_w), (0, 0))
    if kind.startswith("max"):
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        # Reference avg pooling divides by the window clipped to the
        # explicitly-padded extent (CpuMatrix::avgPoolForward, Matrix.cpp:
        # poolSize = (hend-hstart)*(wend-wstart) with hend clipped to
        # height+padding) — so explicit padding counts, ceil-extra doesn't.
        ones = jnp.ones((1, x.shape[1] + 2 * ph, x.shape[2] + 2 * pw, 1), x.dtype)
        counts = lax.reduce_window(
            ones, 0.0, lax.add, window, strides,
            ((0, 0), (0, extra_h), (0, extra_w), (0, 0)),
        )
        out = summed / counts
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# batch_norm — running stats live in layer state; train uses batch stats
# ---------------------------------------------------------------------------


def bn_init(conf, in_confs, rng):
    c = conf.attrs["channels"]
    std = conf.attr("param_std")
    # v1 ParamAttr(initial_std=...) on batch_norm randomizes gamma (the
    # DCGAN-style init); default stays the standard ones
    scale = init.normal(rng, (c,), std) if std else init.ones((c,))
    p = {"scale": scale}
    if conf.bias:
        p["beta"] = init.zeros((c,))
    return p


def bn_init_state(conf, in_confs):
    c = conf.attrs["channels"]
    return {"mean": init.zeros((c,)), "var": init.ones((c,))}


@register_layer("batch_norm", init=bn_init, init_state=bn_init_state)
def batch_norm_apply(conf, params, inputs, ctx):
    a = conf.attrs
    eps = a.get("epsilon", 1e-5)
    momentum = a.get("moving_average_fraction", 0.9)
    img = a.get("in_h") is not None
    in_dtype = inputs[0].data.dtype
    x = inputs[0].data
    if img:
        x = to_nhwc(x, a["in_h"], a["in_w"], a["channels"])
        axes = (0, 1, 2)
    else:
        axes = (0,)
    st = ctx.state.get(conf.name, {})
    use_global = (not ctx.train) or a.get("use_global_stats", False)
    if use_global and st:
        mean, var = st["mean"], st["var"]
    else:
        # Single-pass statistics: E[x] and E[x^2] are sibling reductions
        # over the same input — XLA fuses them into ONE read of the (bf16)
        # activations with f32 accumulation (the casts fuse as producers).
        # jnp.var would serialize a SECOND full pass because it re-reads x
        # against the already-computed mean; across ResNet-50's ~50 BN
        # layers that second pass alone was ~15% of the train step.
        n = 1.0
        for ax in axes:
            n *= x.shape[ax]
        xf = x.astype(jnp.float32)
        mean = jnp.sum(xf, axis=axes) / n
        var = jnp.maximum(
            jnp.sum(jnp.square(xf), axis=axes) / n - jnp.square(mean), 0.0
        )
        if ctx.train and st:
            ctx.new_state[conf.name] = {
                "mean": momentum * st["mean"] + (1 - momentum) * mean,
                "var": momentum * st["var"] + (1 - momentum) * var,
            }
    inv = lax.rsqrt(var + eps)
    # normalize reads x once more in its native dtype; the f32 per-channel
    # scalars broadcast in
    out = (x.astype(jnp.float32) - mean) * inv * params["scale"].astype(
        jnp.float32
    )
    if "beta" in params:  # bias_attr=False BN has no shift
        out = out + params["beta"].astype(jnp.float32)
    return SeqTensor(out.astype(in_dtype), inputs[0].lengths)


@register_layer("norm")
def cmrnorm_apply(conf, params, inputs, ctx):
    """Cross-map response normalization (reference NormLayer "norm" /
    CMRProjectionNormLayer -> function/CrossMapNormalOp.cpp):
    out = x * (1 + scale * sum_{window over channels} x^2)^(-power),
    the AlexNet LRN.  Channel window sum = pad + stacked slices (static
    size; XLA fuses the whole chain)."""
    a = conf.attrs
    size = a["norm_size"]
    scale = a.get("scale", 0.0128)
    power = a.get("power", 0.75)
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    x32 = x.astype(jnp.float32)
    sq = x32 * x32
    # reference window start = -((size-1)/2): for even sizes the window
    # extends one further to the RIGHT (CrossMapNormalOp.cpp)
    half = (size - 1) // 2
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, size - 1 - half)))
    window = sum(
        jax.lax.dynamic_slice_in_dim(padded, k, x.shape[-1], axis=3)
        for k in range(size)
    )
    denom = (1.0 + scale * window) ** (-power)
    return SeqTensor((x32 * denom).astype(x.dtype), inputs[0].lengths)


# ---------------------------------------------------------------------------
# maxout — MaxOutLayer.cpp: max over groups of channels
# ---------------------------------------------------------------------------


@register_layer("maxout")
def maxout_apply(conf, params, inputs, ctx):
    a = conf.attrs
    g = a["groups"]
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    b, h, w, c = x.shape
    out = jnp.max(x.reshape(b, h, w, c // g, g), axis=-1)
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# pad — PadLayer.cpp: zero-pad C/H/W
# ---------------------------------------------------------------------------


@register_layer("pad")
def pad_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    pc, ph, pw = a.get("pad_c", (0, 0)), a.get("pad_h_pair", (0, 0)), a.get(
        "pad_w_pair", (0, 0)
    )
    out = jnp.pad(x, ((0, 0), tuple(ph), tuple(pw), tuple(pc)))
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# crop — CropLayer.cpp
# ---------------------------------------------------------------------------


@register_layer("crop")
def crop_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    oh, ow = a["out_h"], a["out_w"]
    oc = a.get("out_c", a["in_c"])
    offh, offw = a.get("offset_h", 0), a.get("offset_w", 0)
    offc = a.get("offset_c", 0)
    out = x[:, offh : offh + oh, offw : offw + ow, offc : offc + oc]
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# bilinear_interp — BilinearInterpLayer.cpp
# ---------------------------------------------------------------------------


@register_layer("bilinear_interp")
def bilinear_interp_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    b, h, w, c = x.shape
    oh, ow = a["out_h"], a["out_w"]
    out = jax.image.resize(x, (b, oh, ow, c), method="bilinear")
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# spp — SpatialPyramidPoolLayer.cpp: pyramid of pools concatenated
# ---------------------------------------------------------------------------


@register_layer("spp")
def spp_apply(conf, params, inputs, ctx):
    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    b, h, w, c = x.shape
    levels = a.get("pyramid_height", 3)
    kind = a.get("pool_type", "max")
    outs = []
    for lvl in range(levels):
        bins = 2**lvl
        # Split H/W into `bins` cells via strided reduce_window.
        kh, kw = -(-h // bins), -(-w // bins)  # ceil
        pad_h = kh * bins - h
        pad_w = kw * bins - w
        if kind.startswith("max"):
            xp = jnp.pad(
                x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)),
                constant_values=-jnp.inf,
            )
            pooled = lax.reduce_window(
                xp, -jnp.inf, lax.max, (1, kh, kw, 1), (1, kh, kw, 1), "VALID"
            )
        else:
            xp = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
            pooled = (
                lax.reduce_window(
                    xp, 0.0, lax.add, (1, kh, kw, 1), (1, kh, kw, 1), "VALID"
                )
                / (kh * kw)
            )
        outs.append(pooled.reshape(b, -1))
    return SeqTensor(jnp.concatenate(outs, axis=-1), inputs[0].lengths)


# ---------------------------------------------------------------------------
# featmap_expand — FeatureMapExpandLayer.cpp
# ---------------------------------------------------------------------------


@register_layer("featmap_expand")
def featmap_expand_apply(conf, params, inputs, ctx):
    x = inputs[0]
    num_filters = conf.attrs["num_filters"]
    as_row = conf.attrs.get("as_row_vector", True)
    b = x.data.shape[0]
    flat = x.data.reshape(b, -1)
    if as_row:
        out = jnp.tile(flat[:, None, :], (1, num_filters, 1)).reshape(b, -1)
    else:
        out = jnp.tile(flat[:, :, None], (1, 1, num_filters)).reshape(b, -1)
    return SeqTensor(out, x.lengths)
