"""Remaining layer inventory: prelu, power, data_norm, block_expand, rotate,
sub_seq, linear_comb (convex_comb), cos_vm, print, scale_shift, kmax_seq.

Reference: paddle/gserver/layers/{PReluLayer(ParameterReluLayer),PowerLayer,
DataNormLayer,BlockExpandLayer,RotateLayer,SubSequenceLayer,LinearChainCombLayer
(ConvexCombinationLayer),CosSimVecMatLayer,PrintLayer,ScaleShiftLayer,
KmaxSeqScoreLayer}.cpp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer


# ---------------------------------------------------------------------------
# prelu — ParameterReluLayer.cpp: negative-slope parameter shared over groups
# of `partial_sum` consecutive features
# ---------------------------------------------------------------------------


def prelu_init(conf, in_confs, rng):
    partial = conf.attrs.get("partial_sum", 1)
    return {"a": jnp.full((in_confs[0].size // partial,), 0.25)}


@register_layer("prelu", init=prelu_init)
def prelu_apply(conf, params, inputs, ctx):
    x = inputs[0]
    a = params["a"]
    partial = conf.attrs.get("partial_sum", 1)
    slope = jnp.repeat(a, partial)
    return x.with_data(jnp.where(x.data > 0, x.data, slope * x.data))


# ---------------------------------------------------------------------------
# power — PowerLayer.cpp: y = x ^ w, w a per-sample scalar input
# ---------------------------------------------------------------------------


@register_layer("power")
def power_apply(conf, params, inputs, ctx):
    w, x = inputs  # w: [B, 1], x: [B, D]
    return x.with_data(jnp.power(x.data, w.data))


# ---------------------------------------------------------------------------
# data_norm — DataNormLayer.cpp: fixed-statistics normalization.  The stats
# are non-trainable state (set from dataset scan via set_state, like the
# reference loads them from a pre-computed parameter).
# ---------------------------------------------------------------------------


def data_norm_state(conf, in_confs):
    d = in_confs[0].size
    return {
        "mean": init.zeros((d,)),
        "std": init.ones((d,)),
        "min": init.zeros((d,)),
        "max": init.ones((d,)),
    }


@register_layer("data_norm", init_state=data_norm_state)
def data_norm_apply(conf, params, inputs, ctx):
    x = inputs[0]
    st = ctx.state.get(conf.name, {})
    strategy = conf.attrs.get("strategy", "z-score")
    if strategy == "z-score":
        out = (x.data - st["mean"]) / jnp.maximum(st["std"], 1e-12)
    elif strategy == "min-max":
        rng_ = jnp.maximum(st["max"] - st["min"], 1e-12)
        out = (x.data - st["min"]) / rng_
    else:  # decimal-scaling
        scale = jnp.power(
            10.0, jnp.ceil(jnp.log10(jnp.maximum(jnp.abs(st["max"]), 1e-12)))
        )
        out = x.data / scale
    return x.with_data(out)


# ---------------------------------------------------------------------------
# block_expand — BlockExpandLayer.cpp: im2col into a sequence of blocks
# (OCR pipelines: image → block sequence → rnn/ctc).  Output is a sequence
# with static length = num_blocks (every sample the same, lengths full).
# ---------------------------------------------------------------------------


@register_layer("block_expand")
def block_expand_apply(conf, params, inputs, ctx):
    from paddle_tpu.layers.conv import to_nhwc

    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    bh, bw = a["block_y"], a["block_x"]
    sh, sw = a.get("stride_y", 1), a.get("stride_x", 1)
    ph, pw = a.get("padding_y", 0), a.get("padding_x", 0)
    b_ = x.shape[0]
    # NCHW for patch extraction to get channel-major block features
    # (reference emits blocks as C*bh*bw rows).
    patches = lax.conv_general_dilated_patches(
        jnp.moveaxis(x, 3, 1),
        filter_shape=(bh, bw),
        window_strides=(sh, sw),
        padding=[(ph, ph), (pw, pw)],
    )  # [B, C*bh*bw, OH, OW]
    c_blk = patches.shape[1]
    seq = patches.reshape(b_, c_blk, -1).transpose(0, 2, 1)  # [B, OH*OW, F]
    n_blocks = seq.shape[1]
    lengths = jnp.full((b_,), n_blocks, jnp.int32)
    return SeqTensor(seq, lengths)


# ---------------------------------------------------------------------------
# rotate — RotateLayer.cpp: 90° CCW rotation of each feature map
# ---------------------------------------------------------------------------


@register_layer("rotate")
def rotate_apply(conf, params, inputs, ctx):
    from paddle_tpu.layers.conv import to_nhwc

    a = conf.attrs
    x = to_nhwc(inputs[0].data, a["in_h"], a["in_w"], a["in_c"])
    out = jnp.flip(jnp.swapaxes(x, 1, 2), axis=1)  # [B, W, H, C]
    return SeqTensor(out, inputs[0].lengths)


# ---------------------------------------------------------------------------
# sub_seq — SubSequenceLayer.cpp: slice [offset, offset+size) of each sequence
# ---------------------------------------------------------------------------


@register_layer("sub_seq")
def sub_seq_apply(conf, params, inputs, ctx):
    x, off_t, size_t = inputs
    assert x.is_seq
    off = off_t.data.astype(jnp.int32).reshape(-1)  # [B]
    sz = size_t.data.astype(jnp.int32).reshape(-1)  # [B]
    t_ = x.max_len
    idx = jnp.clip(off[:, None] + jnp.arange(t_)[None, :], 0, t_ - 1)
    data = jnp.take_along_axis(
        x.data, idx.reshape(idx.shape + (1,) * (x.data.ndim - 2)), axis=1
    )
    return SeqTensor(data, jnp.minimum(sz, x.lengths - off))


# ---------------------------------------------------------------------------
# linear_comb / convex_comb — LinearCombinationLayer(ConvexCombinationLayer).cpp
# y[d] = sum_m w[m] * x[m, d] with x given flat as [B, M*D]
# ---------------------------------------------------------------------------


@register_layer("linear_comb")
def linear_comb_apply(conf, params, inputs, ctx):
    w, x = inputs  # w: [B, M], x: [B, M*D]
    b_ = w.data.shape[0]
    m = w.data.shape[-1]
    mat = x.data.reshape(b_, m, -1)
    return SeqTensor(jnp.einsum("bm,bmd->bd", w.data, mat), x.lengths)


# ---------------------------------------------------------------------------
# cos_vm — CosSimVecMatLayer.cpp: cosine of a vector with each matrix row
# ---------------------------------------------------------------------------


@register_layer("cos_vm")
def cos_vm_apply(conf, params, inputs, ctx):
    v, m = inputs  # v: [B, D], m: [B, M*D]
    scale = conf.attrs.get("scale", 1.0)
    b_ = v.data.shape[0]
    mat = m.data.reshape(b_, -1, v.data.shape[-1])  # [B, M, D]
    num = jnp.einsum("bd,bmd->bm", v.data, mat)
    den = jnp.linalg.norm(v.data, axis=-1, keepdims=True) * jnp.linalg.norm(
        mat, axis=-1
    )
    return SeqTensor(scale * num / jnp.maximum(den, 1e-12), v.lengths)


# ---------------------------------------------------------------------------
# print — PrintLayer.cpp: host-side debug print, identity pass-through
# ---------------------------------------------------------------------------


@register_layer("print")
def print_apply(conf, params, inputs, ctx):
    x = inputs[0]
    jax.debug.print(conf.attrs.get("format", "{name}: {val}"),
                    name=conf.name, val=x.data)
    return x


# ---------------------------------------------------------------------------
# scale_shift — ScaleShiftLayer.cpp: y = scale * x + shift (learned scalars)
# ---------------------------------------------------------------------------


def scale_shift_init(conf, in_confs, rng):
    p = {"scale": init.ones((1,))}
    if conf.bias:
        p["shift"] = init.zeros((1,))
    return p


@register_layer("scale_shift", init=scale_shift_init)
def scale_shift_apply(conf, params, inputs, ctx):
    x = inputs[0]
    out = params["scale"][0] * x.data
    if "shift" in params:
        out = out + params["shift"][0]
    return x.with_data(out)


# ---------------------------------------------------------------------------
# kmax_seq_score — KmaxSeqScoreLayer.cpp: indices of the top-k scores per seq
# ---------------------------------------------------------------------------


@register_layer("kmax_seq_score", auto_activation=False)
def kmax_seq_score_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    k = conf.attrs.get("beam_size", 1)
    scores = x.data[..., 0] if x.data.ndim == 3 else x.data  # [B, T]
    masked = jnp.where(x.mask(bool), scores, -jnp.inf)
    vals, idx = lax.top_k(masked, k)
    # slots beyond the sample's length get -1 (reference KmaxSeqScoreLayer)
    idx = jnp.where(jnp.isfinite(vals), idx, -1)
    return SeqTensor(idx.astype(jnp.int32))


# ---------------------------------------------------------------------------
# get_output — GetOutputLayer.cpp: select a named auxiliary output of a layer
# (side outputs use the "<layer>@<arg>" convention: lstm_step's "@cell",
# beam_search's "@scores")
# ---------------------------------------------------------------------------


@register_layer("get_output", auto_activation=False)
def get_output_apply(conf, params, inputs, ctx):
    arg = conf.attrs["arg_name"]
    src = conf.inputs[0]
    key = src if arg in ("", "default") else f"{src}@{arg}"
    if key not in ctx.outputs:
        raise KeyError(
            f"{conf.name}: layer {src!r} has no auxiliary output {arg!r} "
            f"(known: {[k for k in ctx.outputs if k.startswith(src)]})"
        )
    return ctx.outputs[key]


# ---------------------------------------------------------------------------
# agent family — AgentLayer.cpp / GatherAgentLayer / ScatterAgentLayer.
# In the reference these wire values across RecurrentGradientMachine frame
# networks; the recurrent_group scan absorbs that role, so here they keep
# their data semantics: agent = identity view, scatter_agent = row
# selection by ids, gather_agent = time-axis concatenation of sequences.
# ---------------------------------------------------------------------------


@register_layer("agent", auto_activation=False)
def agent_apply(conf, params, inputs, ctx):
    return inputs[0]


@register_layer("scatter_agent", auto_activation=False)
def scatter_agent_apply(conf, params, inputs, ctx):
    src, ids_t = inputs
    ids = ids_t.data.astype(jnp.int32).reshape(-1)
    data = jnp.take(src.data, ids, axis=0)
    lengths = None if src.lengths is None else jnp.take(src.lengths, ids, axis=0)
    subs = None if src.sub_lengths is None else jnp.take(src.sub_lengths, ids, axis=0)
    return SeqTensor(data, lengths, subs)


@register_layer("gather_agent", auto_activation=False)
def gather_agent_apply(conf, params, inputs, ctx):
    from paddle_tpu.layers.sequence import seqconcat_apply

    out = inputs[0]
    for nxt in inputs[1:]:
        out = seqconcat_apply(conf, params, [out, nxt], ctx)
    return out
