"""SSD detection layers: priorbox, multibox_loss, detection_output.

Reference: paddle/gserver/layers/{PriorBox,MultiBoxLossLayer,
DetectionOutputLayer}.cpp and the priorbox_layer/multibox_loss_layer/
detection_output_layer DSL (trainer_config_helpers/layers.py:1049-1214).

TPU-native shapes: ground truth is a padded dense sequence slot
[B, G, 6] = (label, xmin, ymin, xmax, ymax, difficult) with per-image valid
counts (reference packs it CSR); detections come out as a fixed
[B, keep_top_k, 6] = (label, score, xmin, ymin, xmax, ymax) block padded
with label -1 (reference emits a variable-row host matrix).  Priors are
compile-time constants folded by XLA."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer
from paddle_tpu.ops import detection as D


# ---------------------------------------------------------------------------
# priorbox
# ---------------------------------------------------------------------------


@register_layer("priorbox", auto_activation=False, full_precision=True)
def priorbox_apply(conf, params, inputs, ctx):
    """Output [B, P, 8]: corner-form normalized prior + its 4 variances
    (reference packs the same 2×P*4)."""
    a = conf.attrs
    priors = jnp.asarray(a["_priors"])  # [P, 4] precomputed at build
    var = jnp.broadcast_to(
        jnp.asarray(a["variance"], jnp.float32)[None, :], priors.shape
    )
    packed = jnp.concatenate([priors, var], axis=-1)  # [P, 8]
    b = inputs[0].batch_size
    return SeqTensor(jnp.broadcast_to(packed[None], (b,) + packed.shape))


# ---------------------------------------------------------------------------
# multibox_loss
# ---------------------------------------------------------------------------


def _gather_preds(inputs, n_loc, width):
    """Reshape each prediction (NHWC conv [B,H,W,k*width] or already-flat
    [B, H*W*k*width]) to [B, P_i, width] and concat along priors — same
    cell-major order as make_priors."""
    return jnp.concatenate(
        [t.data.reshape(t.data.shape[0], -1, width) for t in inputs[:n_loc]],
        axis=1,
    )


@register_layer("multibox_loss", auto_activation=False, full_precision=True)
def multibox_loss_apply(conf, params, inputs, ctx):
    """inputs: (priorbox, label, loc_0..loc_{n-1}, conf_0..conf_{n-1});
    output [B] per-image loss = (smooth-L1 loc + softmax CE conf) / n_pos
    with 2-phase matching and hard negative mining
    (MultiBoxLossLayer::forward)."""
    a = conf.attrs
    n_in = a["input_num"]
    n_cls = a["num_classes"]
    bg = a["background_id"]

    priors_t, label_t = inputs[0], inputs[1]
    locs = _gather_preds(inputs[2 : 2 + n_in], n_in, 4)  # [B, P, 4]
    confs = _gather_preds(inputs[2 + n_in :], n_in, n_cls)  # [B, P, C]
    priors = priors_t.data[0, :, :4]  # [P, 4] (identical across batch)
    variances = priors_t.data[0, 0, 4:]

    gt = label_t.data  # [B, G, 6]
    assert label_t.is_seq
    gt_valid = label_t.mask(jnp.float32) > 0  # [B, G]
    gt_boxes = gt[..., 1:5]
    gt_labels = gt[..., 0].astype(jnp.int32)

    def per_image(loc_p, conf_p, boxes, labels, valid):
        matched, pos, max_iou = D.match_priors(
            priors, boxes, valid, a["overlap_threshold"]
        )
        n_pos = jnp.sum(pos.astype(jnp.float32))
        # localization loss over positives
        target = D.encode_boxes(boxes[matched], priors, variances)
        loc_loss = jnp.sum(
            jnp.sum(D.smooth_l1(loc_p - target), axis=-1) * pos.astype(jnp.float32)
        )
        # confidence loss: positives -> matched class, negatives -> background
        probs = jax.nn.softmax(conf_p, axis=-1)
        logp = jnp.log(jnp.maximum(probs, 1e-12))
        cls = jnp.where(pos, labels[matched], bg)
        ce = -jnp.take_along_axis(logp, cls[:, None], axis=-1)[:, 0]  # [P]
        # hard negative mining: reference ranks negatives by max
        # NON-background confidence (getMaxConfidenceScores), keep ratio
        neg_score = jnp.max(probs.at[:, bg].set(0.0), axis=-1)
        neg_cand = (~pos) & (max_iou < a["neg_overlap"])
        ranks = D.hard_negative_ranks(neg_score, neg_cand)
        n_neg = jnp.minimum(
            a["neg_pos_ratio"] * n_pos, jnp.sum(neg_cand.astype(jnp.float32))
        )
        neg_keep = ranks < n_neg
        conf_loss = jnp.sum(ce * (pos | neg_keep).astype(jnp.float32))
        return loc_loss + conf_loss, n_pos

    raw, n_pos = jax.vmap(per_image)(locs, confs, gt_boxes, gt_labels, gt_valid)
    # Reference normalizes by the BATCH-total match count
    # (MultiBoxLossLayer.cpp:206,257 numMatches_), not per image.  The
    # per-image outputs are scaled so their mean equals
    # sum(raw)/total_matches.
    total = jnp.maximum(jnp.sum(n_pos), 1.0)
    loss = raw * (raw.shape[0] / total)
    return SeqTensor(loss[:, None])


# ---------------------------------------------------------------------------
# detection_output
# ---------------------------------------------------------------------------


@register_layer("detection_output", auto_activation=False, full_precision=True)
def detection_output_apply(conf, params, inputs, ctx):
    """inputs: (priorbox, loc..., conf...); output [B, keep_top_k, 6] =
    (label, score, xmin, ymin, xmax, ymax), empty slots label=-1
    (DetectionOutputLayer::forward: decode + per-class NMS + global top-k)."""
    a = conf.attrs
    n_in = a["input_num"]
    n_cls = a["num_classes"]
    bg = a["background_id"]

    priors_t = inputs[0]
    locs = _gather_preds(inputs[1 : 1 + n_in], n_in, 4)
    confs = _gather_preds(inputs[1 + n_in :], n_in, n_cls)
    priors = priors_t.data[0, :, :4]
    variances = priors_t.data[0, 0, 4:]

    nms_top_k = min(a["nms_top_k"], locs.shape[1])
    keep_top_k = a["keep_top_k"]

    def per_image(loc_p, conf_p):
        boxes = D.decode_boxes(loc_p, priors, variances)  # [P, 4]
        probs = jax.nn.softmax(conf_p, axis=-1)  # [P, C]
        all_scores = []
        all_labels = []
        all_boxes = []
        for c in range(n_cls):
            if c == bg:
                continue
            s = probs[:, c]
            s = jnp.where(s >= a["confidence_threshold"], s, -jnp.inf)
            idx, kept = D.nms(boxes, s, a["nms_threshold"], nms_top_k)
            all_scores.append(kept)
            all_labels.append(jnp.full_like(idx, c))
            all_boxes.append(boxes[idx])
        scores = jnp.concatenate(all_scores)
        labels = jnp.concatenate(all_labels)
        bxs = jnp.concatenate(all_boxes, axis=0)
        k = min(keep_top_k, scores.shape[0])
        top, ti = jax.lax.top_k(scores, k)
        det = jnp.concatenate(
            [
                jnp.where(top > 0, labels[ti], -1).astype(jnp.float32)[:, None],
                jnp.maximum(top, 0.0)[:, None],
                bxs[ti] * (top > 0)[:, None],
            ],
            axis=-1,
        )  # [k, 6]
        if k < keep_top_k:
            pad = jnp.zeros((keep_top_k - k, 6), det.dtype).at[:, 0].set(-1.0)
            det = jnp.concatenate([det, pad], axis=0)
        return det

    return SeqTensor(jax.vmap(per_image)(locs, confs))
