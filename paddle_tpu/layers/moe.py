"""Mixture-of-Experts layer with EXPERT PARALLELISM over the mesh model
axis.

The 2017 reference predates MoE; this is a first-class TPU-native addition
(task spec: distributed modes incl. expert parallelism are first-class).
Design follows the XLA-friendly capacity-based dispatch of Switch/GShard:
top-1 routing, fixed expert capacity C, one-hot dispatch/combine einsums —
all static shapes, so the whole layer jits into dense MXU work.

Under a mesh whose ``model`` axis is >1, the expert-major tensors
([E, C, D] dispatch buffers and the [E, ...] expert weights) carry
``with_sharding_constraint(P('model', ...))``: XLA's SPMD partitioner
places each expert group on its own devices and inserts the token
all-to-all for dispatch/combine — the hand-written NCCL alltoall of
GPU MoE frameworks becomes two sharding annotations.

The router's load-balancing auxiliary (Switch Transformer eq. 4,
``num_experts * Σ_e fraction_e * prob_e``) is exposed as the aux output
``<name>@aux_loss`` for the cost to pick up.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import ApplyContext, register_layer
from paddle_tpu.parallel.mesh import MODEL_AXIS


def moe_init(conf, in_confs, rng):
    d = in_confs[0].size
    e = conf.attr("num_experts")
    h = conf.attr("expert_hidden")
    std = conf.attr("param_std")
    r = jax.random.split(rng, 3)
    # explicit fan-in stds: the default heuristic reads shape[0], which for
    # expert-major [E, D, H] tensors would be 1/sqrt(num_experts)
    p = {
        "router": init.normal(r[0], (d, e), std or init.default_std(d)),
        "w1": init.normal(r[1], (e, d, h), std or init.default_std(d)),
        "w2": init.normal(r[2], (e, h, conf.size), std or init.default_std(h)),
    }
    if conf.bias:
        p["b1"] = init.zeros((e, h))
        p["b2"] = init.zeros((e, conf.size))
    return p


def _expert_sharding(ctx: ApplyContext, conf):
    """NamedSharding for expert-major [E, C, D] buffers when the layer opted
    into model-axis sharding on a >1 model axis, else None."""
    mesh = ctx.mesh
    if (
        mesh is None
        or conf.shard_axis != MODEL_AXIS
        or mesh.shape.get(MODEL_AXIS, 1) <= 1
    ):
        return None
    return NamedSharding(mesh, P(MODEL_AXIS, None, None))


@register_layer("moe", init=moe_init, auto_activation=False)
def moe_apply(conf, params, inputs, ctx: ApplyContext):
    from paddle_tpu.ops.activations import get_activation

    x = inputs[0]
    d = x.data.shape[-1]
    e = conf.attr("num_experts")
    f_act = get_activation(conf.attr("active_type", "relu"))
    cap_factor = conf.attr("capacity_factor", 1.25)

    tokens = x.data.reshape(-1, d)  # [N, D]
    n = tokens.shape[0]
    cap = max(int(n / e * cap_factor), 1)

    logits = tokens @ params["router"].astype(tokens.dtype)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [N, E]
    valid = None
    if x.is_nested:
        valid = x.sub_mask(jnp.float32).reshape(-1)
    elif x.is_seq:
        valid = x.mask(jnp.float32).reshape(-1)
    if valid is not None:
        # padded tokens must not consume expert capacity
        gates = gates * valid[:, None]
    top_gate = jnp.max(gates, axis=-1)  # [N]
    top_idx = jnp.argmax(gates, axis=-1)  # [N]
    onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)
    if valid is not None:
        onehot = onehot * valid[:, None]

    # position of each token within its expert's capacity (exclusive cumsum)
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot  # [N, E]
    keep = (pos < cap).astype(jnp.float32) * onehot
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
    dispatch = keep[..., None] * pos_oh  # [N, E, C]
    combine = dispatch * top_gate[:, None, None]

    sh = _expert_sharding(ctx, conf)
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(tokens.dtype), tokens)
    if sh is not None:
        xin = jax.lax.with_sharding_constraint(xin, sh)
    h = jnp.einsum("ecd,edh->ech", xin, params["w1"])
    if "b1" in params:
        h = h + params["b1"][:, None, :]
    h = f_act(h)
    y = jnp.einsum("ech,ehd->ecd", h, params["w2"])
    if "b2" in params:
        y = y + params["b2"][:, None, :]
    if sh is not None:
        y = jax.lax.with_sharding_constraint(y, sh)
    out = jnp.einsum("nec,ecd->nd", combine.astype(y.dtype), y)  # [N, Dout]

    # Switch load-balance aux: E * sum_e fraction_of_tokens_e * mean_prob_e.
    # Emitted as a per-row [B, 1] tensor where EVERY row equals the scalar
    # aux: the documented pickup (get_output + sum_cost) reduces per ROW
    # (sum_cost sums axis=-1, cost.py) and CompiledNetwork.cost() then takes
    # the batch MEAN — so the effective coefficient is already batch-size
    # invariant (mean of B identical rows = aux).  Do not pre-divide by B.
    denom = jnp.maximum(jnp.sum(onehot), 1.0)
    frac = jnp.sum(onehot, axis=0) / denom
    prob = jnp.sum(gates, axis=0) / denom
    aux = e * jnp.sum(frac * prob)
    ctx.outputs[conf.name + "@aux_loss"] = SeqTensor(
        jnp.broadcast_to(aux, (x.data.shape[0], 1))
    )

    if valid is not None:
        out = out * valid[:, None].astype(out.dtype)
    out = out.reshape(x.data.shape[:-1] + (conf.size,))
    return SeqTensor(out, x.lengths, x.sub_lengths)
