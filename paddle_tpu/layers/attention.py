"""Attention-family layers: multi-head attention, layer norm, positional
encoding — the building blocks of Transformer-base MT (BASELINE.json configs
#5; "new config" stressing the op-graph → HLO lowering, with no reference
implementation to translate).

TPU-native design notes:
  * MHA is two einsums around a masked softmax — XLA fuses the scale/mask/
    softmax chain between the MXU matmuls; heads live in one [B,T,H,dh]
    layout (no per-head loop).
  * Under bf16 mixed precision the softmax and layer-norm statistics compute
    in float32 and cast back: both are cancellation-sensitive reductions.
  * Padding is masked via SeqTensor lengths (keys) and an optional causal
    mask (decoder self-attention) — static shapes, no dynamic slicing.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer
from paddle_tpu.ops import acc_einsum, acc_matmul

NEG_INF = -1e9


# ---------------------------------------------------------------------------
# layer_norm
# ---------------------------------------------------------------------------


def layer_norm_init(conf, in_confs, rng):
    d = conf.size
    return {"gamma": init.ones((d,)), "beta": init.zeros((d,))}


@register_layer("layer_norm", init=layer_norm_init, auto_activation=False)
def layer_norm_apply(conf, params, inputs, ctx):
    x = inputs[0]
    eps = conf.attr("epsilon", 1e-6)
    x32 = x.data.astype(jnp.float32)
    # two-pass (subtract-mean-first) variance on purpose: rows are only
    # 512 wide so the second pass is cheap, and the one-pass E[x^2]-E[x]^2
    # form cancels catastrophically for offset-heavy rows (measured zero
    # speedup here, unlike batch_norm's megasample reductions)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    y = y * params["gamma"].astype(jnp.float32) + params["beta"].astype(jnp.float32)
    return x.with_data(y.astype(x.data.dtype))


# ---------------------------------------------------------------------------
# multi-head attention
# ---------------------------------------------------------------------------


def mha_init(conf, in_confs, rng):
    import jax

    d = conf.size
    d_in_q = in_confs[0].size
    d_in_kv = in_confs[1].size if len(in_confs) > 1 else d_in_q
    rq, rk, rv, ro = jax.random.split(rng, 4)
    std_q = 1.0 / math.sqrt(d_in_q)
    std_kv = 1.0 / math.sqrt(d_in_kv)
    p = {
        "wq": init.normal(rq, (d_in_q, d), std_q),
        "wk": init.normal(rk, (d_in_kv, d), std_kv),
        "wv": init.normal(rv, (d_in_kv, d), std_kv),
        "wo": init.normal(ro, (d, d), 1.0 / math.sqrt(d)),
    }
    if conf.bias:
        p["b"] = init.zeros((d,))
    return p


@register_layer("multi_head_attention", init=mha_init, auto_activation=False)
def mha_apply(conf, params, inputs, ctx):
    """inputs: (query, key_value) — pass the same layer twice for
    self-attention.  attrs: n_heads, causal."""
    q_in = inputs[0]
    kv_in = inputs[1] if len(inputs) > 1 else inputs[0]
    h = conf.attrs["n_heads"]
    causal = conf.attr("causal", False)
    d = conf.size
    dh = d // h
    assert d % h == 0, f"{conf.name}: size {d} not divisible by n_heads {h}"

    # self-attention detection by TOPOLOGY, not object identity: the
    # mixed-precision cast rebuilds each input SeqTensor, so `kv_in is
    # q_in` is False in every bf16 step even when both are the same layer
    same_input = len(conf.inputs) == 1 or conf.inputs[0] == conf.inputs[1]
    if same_input:
        # self-attention: one [D, 3D] GEMM instead of three [D, D] — wider
        # N keeps the MXU fuller and the param concat is trace-time cheap
        qkv = acc_matmul(q_in.data, jnp.concatenate(
            [params["wq"], params["wk"], params["wv"]], axis=1
        ))
        q, k, v = jnp.split(qkv, 3, axis=-1)
    else:
        q = acc_matmul(q_in.data, params["wq"])  # [B, Tq, D]
        k = acc_matmul(kv_in.data, params["wk"])  # [B, Tk, D]
        v = acc_matmul(kv_in.data, params["wv"])
    b, tq = q.shape[0], q.shape[1]
    tk = k.shape[1]
    q = q.reshape(b, tq, h, dh)
    k = k.reshape(b, tk, h, dh)
    v = v.reshape(b, tk, h, dh)

    sp_axis = conf.attr("seq_parallel_axis")
    out = None
    if sp_axis is not None and tq == tk:
        # context parallelism: shard T over the mesh axis and run exact
        # ring attention (parallel/ring_attention.py) instead of the dense
        # [T, T] score matrix — the long-context path.  The mesh comes from
        # the owning network (trainer-scoped), falling back to the process
        # default (compiler.py ApplyContext).
        from paddle_tpu.parallel.ring_attention import (
            sequence_parallel_attention,
        )

        mesh = ctx.mesh
        usable = (
            mesh is not None
            and sp_axis in mesh.shape
            and tq % mesh.shape[sp_axis] == 0
        )
        if not usable:
            import warnings

            if mesh is None:
                why = "no mesh is available"
            elif sp_axis not in mesh.shape:
                why = f"the mesh has no {sp_axis!r} axis"
            else:
                why = (
                    f"T={tq} is not divisible by the "
                    f"{mesh.shape[sp_axis]}-way ring"
                )
            warnings.warn(
                f"{conf.name}: seq_parallel_axis={sp_axis!r} requested but "
                f"{why}; falling back to dense O(T^2) attention",
                stacklevel=2,
            )
        else:
            out = sequence_parallel_attention(
                q, k, v, mesh, sp_axis,
                lengths=kv_in.lengths if kv_in.is_seq else None,
                causal=causal,
            ).reshape(b, tq, d)

    if out is None and tq == tk:
        # Fused flash-attention Pallas kernel (ops/pallas_attention.py):
        # streams k/v blocks through VMEM with an online softmax — no
        # [T, T] score matrix in HBM.  TPU backend only; dense fallback
        # keeps CPU tests and odd shapes exact.
        from paddle_tpu.ops import pallas_attention as fa
        from paddle_tpu.utils.flags import get_flag

        if (
            get_flag("use_pallas_attention")
            and jax.default_backend() == "tpu"
            and fa.supported(tq, dh)
        ):
            bq, bk = fa.auto_blocks(tq)
            out = fa.flash_attention_diff(
                q, k, v,
                kv_in.lengths if kv_in.is_seq else None,
                causal, bq, bk, False,
            ).reshape(b, tq, d)

    if out is None:  # dense path
        # Explicit [B, h, T, dh] operands with LEADING batch dims: the
        # score/output einsums and every dot_general their VJP emits then
        # have (b, h) as proper leading batch dimensions, which the TPU
        # layout assignment handles in place.  With h trapped at dim 2
        # ("bqhd,bkhd->bhqk") the backward materialized layout-change
        # copies of every [B,h,T,T]/[B,T,h,dh] grad — measured 9.1 ms of
        # a 36 ms transformer-base step (25% in pure copies).  (Two
        # alternatives measured SLOWER on v5e: a single packed
        # [B,T,3,h,dh]->[3,B,h,T,dh] relayout of the fused QKV — the 5-D
        # transpose tiles worse than three separate ones — and a
        # whole-[T,T]-in-VMEM Pallas kernel with grid (B,) + in-core
        # batched-over-heads dots, which lost ~35% to tiny per-program
        # work at T=64.)
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        scores = acc_einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(dh)
        scores = scores.astype(jnp.float32)
        if kv_in.is_seq:
            key_mask = kv_in.mask(jnp.float32)  # [B, Tk]
            scores = scores + (1.0 - key_mask)[:, None, None, :] * NEG_INF
        if causal:
            cm = jnp.tril(jnp.ones((tq, tk), jnp.float32))
            scores = scores + (1.0 - cm)[None, None, :, :] * NEG_INF
        w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        out = (
            acc_einsum("bhqk,bhkd->bhqd", w, vh)
            .transpose(0, 2, 1, 3)
            .reshape(b, tq, d)
        )

    out = acc_matmul(out, params["wo"])
    if "b" in params:
        out = out + params["b"]
    return SeqTensor(out, q_in.lengths, q_in.sub_lengths)


# ---------------------------------------------------------------------------
# attention-GRU decoder step pattern — the fused-scan matcher
# ---------------------------------------------------------------------------
#
# The v1 NMT decoder idiom (reference trainer_config_helpers networks.py
# simple_attention feeding a gru_step inside a recurrent_group) builds this
# exact step sub-graph:
#
#   expand(memory, enc_proj) -> fc(identity) --\
#                                 enc_proj -----+-> addto(act) -> fc(1,
#   seq_softmax) -> scaling(scores, enc) -> seqpool(sum) = context
#   fc([context, scanned...], 3H, identity) -> gru_step(., memory)
#
# match_attention_gru_step recognizes it structurally (types, wiring, act/
# bias constraints) so recurrent_group can lower the WHOLE step onto the
# fused custom-VJP scan core (ops/rnn.py _attgru_core) with no config edits
# — the op-fusion analogue of the reference's hand-fused per-timestep
# decoder kernels (paddle/cuda/src/hl_cuda_lstm.cu).  Anything that doesn't
# match keeps the generic per-layer scan body.


@dataclasses.dataclass(frozen=True)
class AttentionGRUMatch:
    """Layer names of a matched attention-GRU decoder step."""

    gru: str  # gru_step — the memory link
    in_proj: str  # fc building the 3H gate input from [context, scanned...]
    pool: str  # seqpool(sum) -> context
    scale: str  # scaling(scores, enc)
    scores: str  # fc size-1 sequence_softmax
    hidden: str  # addto(enc_proj, state_proj)
    state_proj: str  # fc over the expanded memory
    expand: str  # expand(memory, enc_proj)
    mem: str  # memory placeholder name
    enc_name: str  # static placeholder: encoded sequence (context values)
    ep_name: str  # static placeholder: encoded projection (score keys)
    ctx_slot: int  # index of the context input within in_proj.inputs
    scan_slots: Tuple[Tuple[int, str], ...]  # (in_proj slot, scan placeholder)
    gate_act: str
    act: str
    att_act: str
    matched: frozenset  # every matched layer name, for body-coverage checks


def _clean(c) -> bool:
    """No dropout / error-clip / dynamic-width on a candidate layer — the
    fused core implements none of them."""
    return (
        c.drop_rate == 0.0
        and not c.attr("error_clip", 0.0)
        and not c.attr("dynamic_width_in")
    )


# The fused backward derives the score activation's derivative with a
# jvp-against-ones (ops/rnn.py _attgru_core_bwd) — exact ONLY for
# elementwise activations.  A non-elementwise act (softmax, ...) on the
# attention hidden layer must fall back to the generic scan, or it would
# match, run, and train with silently wrong gradients.
_ELEMENTWISE_ATT_ACTS = frozenset({
    "", "identity", "linear", "tanh", "sigmoid", "relu", "brelu",
    "stanh", "softrelu", "abs", "square",
})


def _ident_act(c) -> bool:
    return c.act in ("identity", "linear", "")


def match_attention_gru_step(
    layers, mem_conf, scan_names, static_seq_names
) -> Optional[AttentionGRUMatch]:
    """Match the sub-topology rooted at `mem_conf`'s link against the v1
    attention-GRU decoder idiom.  `layers` is the step sub-topology's
    {name: LayerConf}; `scan_names` the scanned placeholder names;
    `static_seq_names` the sequence-valued static placeholder names.
    Returns None on any structural mismatch (callers fall back to the
    generic scan)."""
    if mem_conf.attrs.get("is_seq") or mem_conf.attrs.get("boot_const_id") is not None:
        return None
    link = mem_conf.attrs.get("link") or ""
    gru = layers.get(link)
    if (
        gru is None
        or gru.type != "gru_step"
        or gru.attr("tied_weights", False)
        or not _clean(gru)
        or len(gru.inputs) != 2
        or gru.inputs[1] != mem_conf.name
    ):
        return None
    h = gru.size
    in_proj = layers.get(gru.inputs[0])
    if (
        in_proj is None
        or in_proj.type != "fc"
        or not _ident_act(in_proj)
        or not _clean(in_proj)
        or in_proj.size != 3 * h
    ):
        return None
    # exactly one in_proj input is the pooled context; the rest must be
    # scanned placeholders (their projections hoist out of the scan)
    ctx_slot = None
    scan_slots = []
    for i, nm in enumerate(in_proj.inputs):
        c = layers.get(nm)
        if c is not None and c.type == "seqpool":
            if ctx_slot is not None:
                return None
            ctx_slot = i
        elif nm in scan_names:
            scan_slots.append((i, nm))
        else:
            return None
    if ctx_slot is None or not scan_slots:
        return None
    pool = layers[in_proj.inputs[ctx_slot]]
    if (
        pool.attr("pool_type", "max") != "sum"
        or pool.attr("agg_level", 0) != 0
        or pool.attr("stride", -1) > 0
        or pool.attr("output_max_index", False)
        or not _ident_act(pool)
        or not _clean(pool)
        or len(pool.inputs) != 1
    ):
        return None
    scale = layers.get(pool.inputs[0])
    if (
        scale is None
        or scale.type != "scaling"
        or not _ident_act(scale)
        or not _clean(scale)
        or len(scale.inputs) != 2
    ):
        return None
    scores_name, enc_name = scale.inputs
    if enc_name not in static_seq_names:
        return None
    scores = layers.get(scores_name)
    if (
        scores is None
        or scores.type != "fc"
        or scores.size != 1
        or scores.act != "sequence_softmax"
        or scores.bias
        or not _clean(scores)
        or len(scores.inputs) != 1
    ):
        return None
    hidden = layers.get(scores.inputs[0])
    if (
        hidden is None
        or hidden.type != "addto"
        or hidden.bias
        or not _clean(hidden)
        or len(hidden.inputs) != 2
        or hidden.act not in _ELEMENTWISE_ATT_ACTS
    ):
        return None
    ep_name = state_proj = None
    for nm in hidden.inputs:
        if nm in static_seq_names:
            ep_name = nm
        else:
            state_proj = layers.get(nm)
    if ep_name is None or state_proj is None:
        return None
    if (
        state_proj.type != "fc"
        or not _ident_act(state_proj)
        or not _clean(state_proj)
        or len(state_proj.inputs) != 1
    ):
        return None
    exp = layers.get(state_proj.inputs[0])
    if (
        exp is None
        or exp.type != "expand"
        or exp.attr("expand_level", 0) != 0
        or not _ident_act(exp)
        or not _clean(exp)
        or tuple(exp.inputs) != (mem_conf.name, ep_name)
    ):
        return None
    matched = frozenset(
        (gru.name, in_proj.name, pool.name, scale.name, scores.name,
         hidden.name, state_proj.name, exp.name)
    )
    return AttentionGRUMatch(
        gru=gru.name,
        in_proj=in_proj.name,
        pool=pool.name,
        scale=scale.name,
        scores=scores.name,
        hidden=hidden.name,
        state_proj=state_proj.name,
        expand=exp.name,
        mem=mem_conf.name,
        enc_name=enc_name,
        ep_name=ep_name,
        ctx_slot=ctx_slot,
        scan_slots=tuple(scan_slots),
        gate_act=gru.attr("gate_act", "sigmoid"),
        act=gru.attr("active_type", "tanh"),
        att_act=hidden.act or "identity",
        matched=matched,
    )


# ---------------------------------------------------------------------------
# sinusoidal positional encoding (parameterless)
# ---------------------------------------------------------------------------


@register_layer("pos_encoding", auto_activation=False)
def pos_encoding_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq and not x.is_nested
    b, t, d = x.data.shape
    scale = conf.attr("emb_scale", 1.0)
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]  # [T, 1]
    div = jnp.exp(
        jnp.arange(0, d, 2, dtype=jnp.float32) * (-math.log(10000.0) / d)
    )
    pe = jnp.zeros((t, d), jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(pos * div))  # ceil(d/2) even channels
    pe = pe.at[:, 1::2].set(jnp.cos(pos * div[: d // 2]))  # floor(d/2) odd
    out = x.data * jnp.asarray(scale, x.data.dtype) + pe.astype(x.data.dtype)
    return x.with_data(out)
