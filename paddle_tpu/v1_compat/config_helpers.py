"""The ``paddle.trainer_config_helpers`` star-import surface for v1 config
files (reference: python/paddle/trainer_config_helpers/__init__.py re-exports
layers + activations + optimizers + poolings + networks + data_sources).

v1 configs do ``from paddle.trainer_config_helpers import *`` then call
`settings()`, `define_py_data_sources2()`, layer functions, and
`outputs()`; ``parse_config`` (v1_compat/__init__.py) installs this module
under that name, executes the config, and collects the declarations below.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

# Layer DSL + networks: configs use the *_layer names and the bare ones.
from paddle_tpu.layers import *  # noqa: F401,F403
from paddle_tpu.layers import layer_math  # noqa: F401
from paddle_tpu.layers import LayerOutput, data as _data_fn
from paddle_tpu.layers.networks import (  # noqa: F401
    bidirectional_gru,
    bidirectional_lstm,
    gru_group,
    gru_unit,
    img_conv_group,
    lstmemory_group,
    lstmemory_unit,
    sequence_conv_pool,
    simple_attention,
    simple_gru,
    simple_gru2,
    simple_img_conv_pool,
    simple_lstm,
    small_vgg,
    vgg_16_network,
)
from paddle_tpu import evaluator as _ev
from paddle_tpu import activation as _A
from paddle_tpu import pooling as _P
from paddle_tpu.v1_compat.raw_face import (  # noqa: F401
    Bias,
    ContextProjection,
    DotMulProjection,
    Evaluator,
    FullMatrixProjection,
    IdentityOffsetProjection,
    IdentityProjection,
    Input,
    Layer,
    Memory,
    RecurrentLayerGroupBegin,
    RecurrentLayerGroupEnd,
    TableProjection,
    TransposedFullMatrixProjection,
    model_type,
)
from paddle_tpu.attr import ExtraAttr, ParamAttr
from paddle_tpu.core import data_types as _dt

# -- v1 class-name aliases ---------------------------------------------------

# Activations (reference activations.py: <Name>Activation classes)
IdentityActivation = _A.Identity
LinearActivation = _A.Linear
SigmoidActivation = _A.Sigmoid
SoftmaxActivation = _A.Softmax
SequenceSoftmaxActivation = _A.SequenceSoftmax
ReluActivation = _A.Relu
BReluActivation = _A.BRelu
TanhActivation = _A.Tanh
STanhActivation = _A.STanh
SoftReluActivation = _A.SoftRelu
AbsActivation = _A.Abs
SquareActivation = _A.Square
ExpActivation = _A.Exp
LogActivation = _A.Log

# Poolings (reference poolings.py)
MaxPooling = _P.Max
AvgPooling = _P.Avg
SumPooling = _P.Sum
SquareRootNPooling = _P.SquareRootN
CudnnMaxPooling = _P.CudnnMax
CudnnAvgPooling = _P.CudnnAvg

# Attributes
ParameterAttribute = ParamAttr
ExtraLayerAttribute = ExtraAttr
ExtraAttribute = ExtraAttr

# conv_layer is the v1 name for img_conv
conv_layer = img_conv  # noqa: F405


def data_layer(
    name: str, size: int, height: int = 0, width: int = 0, layer_attr=None
) -> LayerOutput:
    """v1 data_layer: declares only a size; the slot's real input type comes
    from the data provider and is resolved by parse_config (reference
    config_parser.py DataLayer + DataProvider ownership of types)."""
    lo = _data_fn(name, _dt.dense_vector(size), height=height, width=width)
    lo.conf.attrs["_v1_size_only"] = True
    return lo


# -- optimizers (reference trainer_config_helpers/optimizers.py) -------------


class BaseSGDOptimizer:
    """Carries the learning-method choice; settings() maps it (plus the
    shared learning-rate/regularization arguments) onto paddle_tpu.optimizer
    classes via make_optimizer."""

    kind = "sgd"
    extra: Dict[str, Any] = {}


class MomentumOptimizer(BaseSGDOptimizer):
    kind = "momentum"

    def __init__(self, momentum: float = 0.9, sparse: bool = False):
        self.extra = {"momentum": momentum}


class AdamOptimizer(BaseSGDOptimizer):
    kind = "adam"

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        self.extra = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}


class AdamaxOptimizer(BaseSGDOptimizer):
    kind = "adamax"

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999):
        self.extra = {"beta1": beta1, "beta2": beta2}


class AdaGradOptimizer(BaseSGDOptimizer):
    kind = "adagrad"

    def __init__(self):
        self.extra = {}


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    kind = "decayed_adagrad"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.extra = {"rho": rho, "epsilon": epsilon}


class AdaDeltaOptimizer(BaseSGDOptimizer):
    kind = "adadelta"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.extra = {"rho": rho, "epsilon": epsilon}


class RMSPropOptimizer(BaseSGDOptimizer):
    kind = "rmsprop"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.extra = {"rho": rho, "epsilon": epsilon}


class BaseRegularization:
    pass


class L2Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate


class L1Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate


class ModelAverage:
    def __init__(self, average_window: float, max_average_window: Optional[int] = None):
        self.average_window = average_window
        self.max_average_window = max_average_window


# -- parse-time collected state ----------------------------------------------


@dataclasses.dataclass
class TrainerSettings:
    """What settings() declared (reference optimizers.py:358)."""

    batch_size: int = 1
    learning_rate: float = 1e-3
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    # 'seg0:rate0,seg1:rate1,...' for manual/pass_manual
    # (LearningRateScheduler.cpp ManualLRS)
    learning_rate_args: str = ""
    learning_method: Optional[BaseSGDOptimizer] = None
    regularization: Optional[BaseRegularization] = None
    model_average: Optional[ModelAverage] = None
    gradient_clipping_threshold: float = 0.0
    is_async: bool = False


@dataclasses.dataclass
class DataSources:
    """What define_py_data_sources2 declared (reference data_sources.py:158)."""

    train_list: Optional[str] = None
    test_list: Optional[str] = None
    module: Optional[str] = None
    obj: Optional[str] = None
    test_obj: Optional[str] = None
    args: Optional[dict] = None
    # split datasource: a different provider module for the test stream
    test_module: Optional[str] = None


class _ParseState:
    def __init__(self, config_args: Dict[str, str]):
        self.config_args = config_args
        self.settings = TrainerSettings()
        self.data_sources: Optional[DataSources] = None
        self.train_data: Optional[DataConfig] = None
        self.test_data: Optional[DataConfig] = None
        self.inputs: List[LayerOutput] = []
        self.outputs: List[LayerOutput] = []
        self.evaluators: List[Any] = []
        self.input_names: List[str] = []
        self.pending_output_names: List[str] = []
        self.all_layers: Dict[str, LayerOutput] = {}
        # model_type('multi_nn') ensembles (reference MultiNetwork.cpp,
        # ModelConfig.proto:579 SubModelConfig): each SubModelBegin/End
        # block records its own Inputs/Outputs
        self.model_type_name: Optional[str] = None
        self.submodels: List[dict] = []
        self.submodel_stack: List[dict] = []


_state: Optional[_ParseState] = None


def _require_state() -> _ParseState:
    assert _state is not None, (
        "v1 config helpers must run under paddle_tpu.v1_compat.parse_config"
    )
    return _state


def get_config_arg(name: str, type_, default=None):
    """reference config_parser.py:3581 — typed lookup into the
    ``--config_args`` k=v list given to parse_config."""
    st = _require_state()
    if name not in st.config_args:
        return default
    v = st.config_args[name]
    if type_ is bool:
        return str(v).lower() in ("1", "true", "yes")
    return type_(v)


def settings(batch_size, **kw):
    st = _require_state()
    s = st.settings
    s.batch_size = batch_size
    for k, v in kw.items():
        if not hasattr(s, k):
            raise TypeError(f"settings() got unexpected argument {k!r}")
        setattr(s, k, v)
    # poly schedule with zero decay is the reference default; treat as constant
    if s.learning_rate_schedule == "poly" and s.learning_rate_decay_a == 0.0:
        s.learning_rate_schedule = "constant"


_METHOD_BY_NAME = {
    "momentum": lambda: MomentumOptimizer(),
    "sgd": lambda: MomentumOptimizer(momentum=0.0),
    "adam": lambda: AdamOptimizer(),
    "adamax": lambda: AdamaxOptimizer(),
    "adagrad": lambda: AdaGradOptimizer(),
    "decayed_adagrad": lambda: DecayedAdaGradOptimizer(),
    "adadelta": lambda: AdaDeltaOptimizer(),
    "rmsprop": lambda: RMSPropOptimizer(),
}


def Settings(batch_size=1, learning_rate=1e-3, algorithm="sgd", **kw):
    """The older capital-S config_parser.Settings() face (model_zoo-era
    configs): maps onto settings(); string learning_method names resolve to
    the optimizer classes; unrecognized knobs are ignored like the
    reference's tolerant kwargs handling."""
    st = _require_state()
    st.settings.batch_size = batch_size
    st.settings.learning_rate = learning_rate
    for k, v in kw.items():
        if k == "learning_method" and isinstance(v, str):
            if v not in _METHOD_BY_NAME:
                raise ValueError(
                    f"unknown learning_method {v!r}; supported: "
                    f"{sorted(_METHOD_BY_NAME)}"
                )
            existing = st.settings.learning_method
            if existing is not None and existing.kind == v:
                continue  # keep e.g. default_momentum()'s configured instance
            v = _METHOD_BY_NAME[v]()
        if hasattr(st.settings, k):
            setattr(st.settings, k, v)


@dataclasses.dataclass
class DataConfig:
    """Old-face data declaration (reference config_parser.py SimpleData:986,
    ProtoData, PyData): records the provider kind + its knobs; the TPU data
    plane reads these as plain config, the reference's C++ providers are
    replaced by the reader pipeline."""

    kind: str = "simple"
    files: Optional[str] = None
    feat_dim: Optional[int] = None
    context_len: int = 0
    buffer_capacity: int = 0
    type: Optional[str] = None
    load_data_module: Optional[str] = None
    load_data_object: Optional[str] = None
    load_data_args: Optional[str] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


def SimpleData(files=None, feat_dim=None, context_len=0, buffer_capacity=0,
               **kw):
    return DataConfig(
        kind="simple", files=files, feat_dim=feat_dim,
        context_len=context_len or 0, buffer_capacity=buffer_capacity,
        extra=kw,
    )


def ProtoData(files=None, type=None, feat_dim=None, buffer_capacity=0, **kw):
    return DataConfig(
        kind="proto", files=files, type=type, feat_dim=feat_dim,
        buffer_capacity=buffer_capacity, extra=kw,
    )


def PyData(files=None, type=None, load_data_module=None,
           load_data_object=None, load_data_args=None, **kw):
    return DataConfig(
        kind="py", files=files, type=type,
        load_data_module=load_data_module, load_data_object=load_data_object,
        load_data_args=load_data_args, extra=kw,
    )


def TrainData(data_config, async_load_data=None):
    """reference config_parser.py:1115 — declare the training data config."""
    _require_state().train_data = data_config


def TestData(data_config, async_load_data=None):
    """reference config_parser.py:1127."""
    _require_state().test_data = data_config


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    st = _require_state()
    if isinstance(obj, (list, tuple)):
        obj, test_obj = obj
    else:
        test_obj = obj
    if isinstance(module, (list, tuple)):
        # split datasource: [train_module, test_module] (reference
        # data_sources.py define_py_data_sources list form)
        module, test_module = module
    else:
        test_module = module
    st.data_sources = DataSources(
        train_list=train_list, test_list=test_list, module=module,
        obj=obj, test_obj=test_obj, args=args, test_module=test_module,
    )


def Inputs(*names):
    """Capital-I config_parser form: declares input LAYER NAMES (strings)
    and PINS the feeding order — "the data streams from DataProvider must
    have the same order" (reference config_parser.py:205-222).  parse_config
    copies this order onto Topology.input_order; without it feeding order is
    DFS from the outputs.  Inside a SubModelBegin block the names belong to
    that sub-model (multi_nn groups slots per sub-network the way the
    reference splits inArgs by dataId, MultiNetwork.cpp:70)."""
    st = _require_state()
    if st.submodel_stack:
        st.submodel_stack[-1]["inputs"].extend(names)
    else:
        # APPEND, like the reference (config_parser.py:212 appends each name
        # to input_layer_names) — configs may declare Inputs incrementally
        st.input_names.extend(names)


def Outputs(*names):
    """Capital-O form: output layer NAMES (strings) — parse_config resolves
    them against every layer built during the exec (LayerOutput sink).
    Inside a SubModelBegin block the names are that sub-model's outputs."""
    st = _require_state()
    if st.submodel_stack:
        st.submodel_stack[-1]["outputs"].extend(names)
    else:
        st.pending_output_names = list(names)


def SubModelBegin(name):
    """Open a sub-model block (reference config_parser.py:249; consumed by
    MultiNetwork for model_type('multi_nn') ensembles).  Layers share one
    global namespace and parameter table across sub-models, exactly as the
    reference's MultiNetwork keeps all Parameters on the root network."""
    st = _require_state()
    if any(sm["name"] == name for sm in st.submodels):
        raise ValueError(f"Duplicated submodel name: {name}")
    sm = {"name": name, "inputs": [], "outputs": []}
    st.submodels.append(sm)
    st.submodel_stack.append(sm)


def SubModelEnd(name=None):
    """Close the current sub-model block (reference config_parser.py:265)."""
    st = _require_state()
    assert st.submodel_stack, "SubModelEnd without SubModelBegin"
    sm = st.submodel_stack.pop()
    if name is not None and sm["name"] != name:
        raise ValueError(f"SubModelEnd({name!r}) closes submodel {sm['name']!r}")


def inputs(*layers_):
    st = _require_state()
    flat: List[LayerOutput] = []
    for l in layers_:
        flat.extend(l if isinstance(l, (list, tuple)) else [l])
    st.inputs = flat


def outputs(*layers_):
    st = _require_state()
    flat: List[LayerOutput] = []
    for l in layers_:
        flat.extend(l if isinstance(l, (list, tuple)) else [l])
    st.outputs.extend(flat)


def default_device(device_id: int) -> None:
    """v1 global device selector — a no-op on TPU (placement is mesh-driven;
    reference config_parser default_device sets per-layer device ids)."""


def default_momentum(momentum: float) -> None:
    """v1 global default — folded into settings().learning_method here;
    recorded so make_optimizer can apply it when settings() didn't name a
    momentum."""
    st = _require_state()
    if st.settings.learning_method is None:
        st.settings.learning_method = MomentumOptimizer(momentum=momentum)


def default_decay_rate(rate: float) -> None:
    """v1 global weight-decay default -> settings().regularization."""
    st = _require_state()
    if st.settings.regularization is None:
        st.settings.regularization = L2Regularization(rate)


def default_initial_std(std: float) -> None:
    """Accepted for config compatibility (per-layer ParamAttr initial_std is
    the supported path)."""


def default_initial_mean(mean: float) -> None:
    """Accepted for config compatibility."""


def _recording_evaluator(fn):
    def wrapper(*args, **kw):
        ev = fn(*args, **kw)
        if _state is not None:
            _state.evaluators.append(ev)
        return ev

    wrapper.__name__ = fn.__name__
    return wrapper


# Evaluator declarations (reference trainer_config_helpers/evaluators.py):
# calling one inside a config registers it with the parse result.
classification_error_evaluator = _recording_evaluator(_ev.classification_error_evaluator)
sum_evaluator = _recording_evaluator(_ev.sum_evaluator)
column_sum_evaluator = _recording_evaluator(_ev.column_sum_evaluator)
auc_evaluator = _recording_evaluator(_ev.auc_evaluator)
precision_recall_evaluator = _recording_evaluator(_ev.precision_recall_evaluator)
pnpair_evaluator = _recording_evaluator(_ev.pnpair_evaluator)
ctc_error_evaluator = _recording_evaluator(_ev.ctc_error_evaluator)
chunk_evaluator = _recording_evaluator(_ev.chunk_evaluator)
detection_map_evaluator = _recording_evaluator(_ev.detection_map_evaluator)
value_printer_evaluator = _recording_evaluator(_ev.value_printer_evaluator)
maxid_printer_evaluator = _recording_evaluator(_ev.maxid_printer_evaluator)
maxframe_printer_evaluator = _recording_evaluator(_ev.maxframe_printer_evaluator)
classification_error_printer_evaluator = _recording_evaluator(
    _ev.classification_error_printer_evaluator
)
gradient_printer_evaluator = _recording_evaluator(_ev.gradient_printer_evaluator)
seqtext_printer_evaluator = _recording_evaluator(_ev.seq_text_printer_evaluator)
