"""The RAW config_parser primitive face — ``Layer(...)``, ``Input(...)``,
``Memory``, ``RecurrentLayerGroupBegin/End``, ``Evaluator`` and friends
(reference: python/paddle/trainer/config_parser.py @config_func/@config_layer
registry, :163-184; RecurrentLayerGroupBegin/End :366-386).

The reference's oldest .conf files (paddle/trainer/tests/*.conf,
demo-era configs) build the model by calling these primitives directly —
no trainer_config_helpers import.  Here each call dispatches onto the
typed layer DSL, resolving input names against the layers built so far
(parse_config's layer sink) or the current raw recurrent-group scope.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from paddle_tpu import activation as _A
from paddle_tpu.attr import ExtraAttr, ParamAttr
from paddle_tpu.core.topology import LayerOutput
from paddle_tpu import layers as L

# the MODULE (the package attribute of the same name is the DSL function)
from importlib import import_module

_rg = import_module("paddle_tpu.layers.recurrent_group")

__all__ = [
    "model_type", "Layer", "Input", "Bias", "Memory", "Evaluator",
    "FullMatrixProjection", "TransposedFullMatrixProjection",
    "TableProjection", "IdentityProjection", "IdentityOffsetProjection",
    "DotMulProjection", "ContextProjection",
    "RecurrentLayerGroupBegin", "RecurrentLayerGroupEnd",
]


def _state():
    from paddle_tpu.v1_compat import config_helpers as H

    return H._require_state()


# ---------------------------------------------------------------------------
# raw recurrent-group scope
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _RawGroup:
    name: str
    scanned: List[LayerOutput]
    sub_scanned: List[bool]
    placeholders: List[LayerOutput]
    reverse: bool
    out_links: List[str]
    gb: Any  # _GroupBuild
    created: Dict[str, LayerOutput]
    namespace: Dict[str, LayerOutput]  # in-group name -> layer
    _trace_cm: Any = None


_current_raw_group: Optional[_RawGroup] = None


def reset_raw_state() -> None:
    """Abort any open raw layer group (parse_config error path): exits the
    trace context and clears the module global so one malformed config
    cannot poison later parses in the same process."""
    global _current_raw_group
    g = _current_raw_group
    if g is None:
        return
    _current_raw_group = None
    if g._trace_cm is not None:
        g._trace_cm.__exit__(None, None, None)


def _resolve(name) -> LayerOutput:
    """Resolve a layer reference: in-group names first (incl. the scan
    placeholders standing in for in_links), then the global parse state."""
    if isinstance(name, LayerOutput):
        return name
    g = _current_raw_group
    if g is not None and name in g.namespace:
        return g.namespace[name]
    st = _state()
    if name in st.all_layers:
        return st.all_layers[name]
    raise KeyError(f"raw config references unknown layer {name!r}")


def _register(name: str, lo: LayerOutput) -> None:
    if _current_raw_group is not None:
        _current_raw_group.namespace[name] = lo
    # the global sink (parse_config) records every LayerOutput already


# ---------------------------------------------------------------------------
# input / projection / bias specs
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Ref:
    """A reference to another layer, optionally naming its parameter
    (reference Input(...) / projection config objects)."""

    kind: str
    input_layer_name: Any
    parameter_name: Optional[str] = None
    initial_std: Optional[float] = None
    sparse_update: bool = False
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def param_attr(self) -> Optional[ParamAttr]:
        if self.parameter_name is None and self.initial_std is None:
            return None
        return ParamAttr(
            name=self.parameter_name,
            initial_std=self.initial_std,
            sparse_update=self.sparse_update,
        )


def Input(input_layer_name, parameter_name=None, initial_std=None, **kw):
    return _Ref("input", input_layer_name, parameter_name, initial_std,
                extra=kw)


def FullMatrixProjection(input_layer_name, parameter_name=None,
                         initial_std=None, **kw):
    return _Ref("full_matrix", input_layer_name, parameter_name, initial_std,
                extra=kw)


def TransposedFullMatrixProjection(input_layer_name, parameter_name=None,
                                   initial_std=None, **kw):
    return _Ref("trans_full_matrix", input_layer_name, parameter_name,
                initial_std, extra=kw)


def TableProjection(input_layer_name, parameter_name=None, initial_std=None,
                    sparse_update=False, **kw):
    return _Ref("table", input_layer_name, parameter_name, initial_std,
                sparse_update=bool(sparse_update), extra=kw)


def IdentityProjection(input_layer_name, **kw):
    return _Ref("identity", input_layer_name, extra=kw)


def IdentityOffsetProjection(input_layer_name, offset=0, **kw):
    return _Ref("identity_offset", input_layer_name,
                extra={"offset": offset, **kw})


def DotMulProjection(input_layer_name, parameter_name=None, initial_std=None,
                     **kw):
    return _Ref("dotmul", input_layer_name, parameter_name, initial_std,
                extra=kw)


def ContextProjection(input_layer_name, context_length=3, context_start=None,
                      **kw):
    return _Ref("context", input_layer_name,
                extra={"context_length": context_length,
                       "context_start": context_start, **kw})


@dataclasses.dataclass
class _BiasSpec:
    parameter_name: Optional[str] = None
    initial_std: Optional[float] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


def Bias(parameter_name=None, initial_std=None, **kw):
    return _BiasSpec(parameter_name, initial_std, extra=kw)


def _bias_attr(bias):
    """Raw `bias` values: True/False/Bias(...) -> DSL bias_attr."""
    if isinstance(bias, _BiasSpec):
        return ParamAttr(name=bias.parameter_name,
                         initial_std=bias.initial_std)
    return bias


def _act(active_type: str):
    if not active_type or active_type == "linear":
        return _A.Identity()
    return active_type  # act_name validates registry names


def _as_refs(inputs) -> List[_Ref]:
    items = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    out = []
    for it in items:
        if isinstance(it, _Ref):
            out.append(it)
        else:  # bare string / LayerOutput = plain input
            out.append(_Ref("input", it))
    return out


# ---------------------------------------------------------------------------
# config functions
# ---------------------------------------------------------------------------


def model_type(name: str) -> None:
    """reference config_parser.model_type — 'nn' / 'recurrent_nn'; the TPU
    engine compiles both the same way, so this only records the intent."""
    _state().model_type_name = name


def Memory(name: str, size: int, boot_layer: Optional[str] = None,
           boot_with_const_id: Optional[int] = None,
           is_sequence: bool = False, **kw) -> str:
    """Declare a memory of in-group layer `name` (reference Memory config
    func); returns the handle name projections can reference.
    is_sequence=True carries the linked layer's WHOLE sequence between
    steps (reference sequence-memory frames — see layers/recurrent_group.py
    memory(is_seq=True))."""
    assert _current_raw_group is not None, "Memory() outside a layer group"
    if kw:
        raise TypeError(f"raw Memory() got unsupported arguments {sorted(kw)}")
    boot = _resolve(boot_layer) if boot_layer is not None else None
    mem = _rg.memory(
        name=name, size=size, boot_layer=boot,
        boot_with_const_id=boot_with_const_id,
        is_seq=bool(is_sequence),
    )
    handle = mem.conf.name
    _current_raw_group.namespace[handle] = mem
    return handle


def RecurrentLayerGroupBegin(name: str, in_links, out_links,
                             seq_reversed: bool = False,
                             generator=None) -> None:
    """reference config_parser.py:366 — open a recurrent layer group; the
    Layer() calls until End build the step body; in_links become scan
    placeholders under their own names."""
    global _current_raw_group
    assert _current_raw_group is None, "nested raw layer groups: use the DSL"
    assert generator is None, (
        "raw generator groups are not supported; use beam_search()"
    )
    in_names = list(in_links) if isinstance(in_links, (list, tuple)) else [in_links]
    scanned = [_resolve(n) for n in in_names]
    sub_scanned = [False] * len(scanned)
    step_args, scan_ph, _ = _rg._make_placeholders(name, scanned, sub_scanned, [])

    g = _RawGroup(
        name=name, scanned=scanned, sub_scanned=sub_scanned,
        placeholders=scan_ph, reverse=bool(seq_reversed),
        out_links=list(out_links) if isinstance(out_links, (list, tuple))
        else [out_links],
        gb=None, created={}, namespace={},
    )
    for n, arg in zip(in_names, step_args):
        g.namespace[n] = arg  # in-group references hit the placeholder

    g._trace_cm = _rg._trace_capture()
    g.gb, g.created = g._trace_cm.__enter__()
    _current_raw_group = g


def RecurrentLayerGroupEnd(name: str) -> None:
    """reference config_parser.py:386 — close the group, lower it to one
    recurrent_group layer, and publish the out_link under its name."""
    global _current_raw_group

    g = _current_raw_group
    assert g is not None and g.name == name, (
        f"RecurrentLayerGroupEnd({name!r}) without matching Begin"
    )
    g._trace_cm.__exit__(None, None, None)
    _current_raw_group = None

    assert len(g.out_links) == 1, "raw groups publish exactly one out_link"
    out_name = g.out_links[0]
    step_out = g.namespace.get(out_name)
    assert step_out is not None, (
        f"group {name!r} never built its out_link layer {out_name!r}"
    )
    group = _rg._finalize_group(
        name, g.scanned, g.sub_scanned, [], g.placeholders, [], g.gb,
        g.created, [step_out], g.reverse,
    )
    # The outer network references the result by the OUT-LINK name
    # (reference publishes the scoped layer under it).
    _state().all_layers[out_name] = group


def Evaluator(name: str, type: str, inputs, **kw):
    """reference @config_func Evaluator — records a paddle_tpu evaluator
    bound to the named layers."""
    from paddle_tpu import evaluator as E

    refs = [_resolve(getattr(r, "input_layer_name", r)) for r in _as_refs(inputs)]
    factory = {
        "sum": lambda: E.sum_evaluator(input=refs[0], name=name),
        "column_sum": lambda: E.column_sum_evaluator(input=refs[0], name=name),
        "classification_error": lambda: E.classification_error_evaluator(
            input=refs[0], label=refs[1], name=name
        ),
        "chunk": lambda: E.chunk_evaluator(
            input=refs[0], label=refs[1],
            chunk_scheme=kw.get("chunk_scheme", "IOB"),
            num_chunk_types=kw.get("num_chunk_types", 1), name=name,
        ),
        "value_printer": lambda: E.value_printer_evaluator(
            input=refs[0], name=name
        ),
        "max_id_printer": lambda: E.maxid_printer_evaluator(
            input=refs[0], name=name
        ),
        "max_frame_printer": lambda: E.maxframe_printer_evaluator(
            input=refs[0], name=name
        ),
        "classification_error_printer": (
            lambda: E.classification_error_printer_evaluator(
                input=refs[0], label=refs[1], name=name
            )
        ),
    }.get(type)
    if factory is None:
        raise KeyError(f"raw Evaluator type {type!r} not supported")
    ev = factory()
    _state().evaluators.append(ev)
    return ev


# layer-type dispatch ---------------------------------------------------------


def _build_mixed(name, size, refs, act, bias, **kw):
    projs = []
    for r in refs:
        lo = _resolve(r.input_layer_name)
        pa = r.param_attr()
        if r.kind == "full_matrix":
            projs.append(L.full_matrix_projection(lo, param_attr=pa))
        elif r.kind == "trans_full_matrix":
            projs.append(L.trans_full_matrix_projection(lo, param_attr=pa))
        elif r.kind == "table":
            projs.append(L.table_projection(lo, param_attr=pa))
        elif r.kind == "identity" or r.kind == "input":
            projs.append(L.identity_projection(lo))
        elif r.kind == "identity_offset":
            projs.append(
                L.identity_projection(lo, offset=r.extra["offset"], size=size)
            )
        elif r.kind == "dotmul":
            projs.append(L.dotmul_projection(lo, param_attr=pa))
        elif r.kind == "context":
            projs.append(
                L.context_projection(
                    lo, context_len=r.extra["context_length"],
                    context_start=r.extra.get("context_start"),
                )
            )
        else:
            raise KeyError(f"projection kind {r.kind!r} in raw mixed layer")
    return L.mixed(size=size, input=projs, name=name, act=act, bias_attr=bias)


def Layer(name: str, type: str, size: int = 0, active_type: str = "",
          bias=True, inputs=(), device=None, **kw) -> LayerOutput:
    """reference @config_layer dispatch: build layer `type` from named
    inputs.  Covers the types the reference's raw .conf fixtures use."""
    refs = _as_refs(inputs)
    act = _act(active_type)
    battr = _bias_attr(bias)

    if type == "data":
        from paddle_tpu.v1_compat.config_helpers import data_layer

        lo = data_layer(name=name, size=size)
    elif type == "fc":
        ins = [_resolve(r.input_layer_name) for r in refs]
        pas = [r.param_attr() or ParamAttr() for r in refs]
        lo = L.fc(ins, size=size, act=act, bias_attr=battr, param_attr=pas,
                  name=name)
    elif type == "mixed":
        lo = _build_mixed(name, size, refs, act, battr, **kw)
    elif type == "embedding":
        lo = L.embedding(_resolve(refs[0].input_layer_name), size=size,
                         param_attr=refs[0].param_attr(), name=name)
    elif type == "seqlastins":
        lo = L.last_seq(input=_resolve(refs[0].input_layer_name), name=name)
    elif type == "seqfirstins":
        lo = L.first_seq(input=_resolve(refs[0].input_layer_name), name=name)
    elif type in ("average", "max"):
        from paddle_tpu import pooling as P

        pt = P.Max() if type == "max" else P.Avg()
        lo = L.pooling(_resolve(refs[0].input_layer_name), pt, name=name)
    elif type == "recurrent":
        lo = L.recurrent(
            _resolve(refs[0].input_layer_name), act=act, bias_attr=battr,
            param_attr=refs[0].param_attr(),
            reverse=bool(kw.get("reversed", kw.get("seq_reversed", False))),
            name=name,
        )
    elif type == "rank-cost":
        ins = [_resolve(r.input_layer_name) for r in refs]
        lo = L.rank_cost(ins[0], ins[1], ins[2], name=name)
    elif type == "crf":
        lo = L.crf(
            _resolve(refs[0].input_layer_name),
            _resolve(refs[1].input_layer_name),
            size=size, param_attr=refs[0].param_attr(), name=name,
        )
    elif type == "crf_decoding":
        lo = L.crf_decoding(
            _resolve(refs[0].input_layer_name),
            size=size,
            label=_resolve(refs[1].input_layer_name) if len(refs) > 1 else None,
            param_attr=refs[0].param_attr(), name=name,
        )
    elif type == "multi-class-cross-entropy":
        lo = L.cross_entropy_cost(
            _resolve(refs[0].input_layer_name),
            _resolve(refs[1].input_layer_name), name=name,
        )
    elif type == "square_error":
        lo = L.square_error_cost(
            _resolve(refs[0].input_layer_name),
            _resolve(refs[1].input_layer_name), name=name,
        )
    else:
        raise KeyError(f"raw Layer type {type!r} not supported")
    _register(name, lo)
    return lo
