"""v1 config-file compatibility — run reference-era trainer configs
unmodified (north star: v1_api_demo configs run on TPU).

``parse_config(path, config_arg_str)`` mirrors the reference entry
(python/paddle/trainer/config_parser.py:3669 parse_config): it installs
``paddle.trainer_config_helpers`` / ``paddle.trainer.PyDataProvider2`` import
shims, executes the config file, and returns a :class:`ParsedConfig` holding
the built Topology, trainer settings, and data-source declarations — instead
of the reference's protobuf TrainerConfig.

Data-layer input types: v1 ``data_layer`` declares only a size; the real slot
types belong to the data provider (reference DataProvider2 ownership).  After
executing the config we import the declared provider module and resolve each
data layer's InputType from the @provider declaration, so feeding/training
work end to end.
"""

from __future__ import annotations

import dataclasses
import importlib
import importlib.util
import os
import sys
import threading
import types
import warnings
from typing import Dict, List, Optional

from paddle_tpu.core.topology import LayerConf, Topology

from paddle_tpu.v1_compat import config_helpers as _helpers
from paddle_tpu.v1_compat.config_helpers import (  # noqa: F401
    DataSources,
    TrainerSettings,
)

__all__ = [
    "parse_config",
    "ParsedConfig",
    "make_optimizer",
    "make_data_reader",
    "make_provider_reader",
    "make_config_reader",
    "make_batched_reader",
]


def _install_import_shims() -> None:
    """Make ``paddle.trainer_config_helpers`` / ``paddle.trainer.
    PyDataProvider2`` importable (configs and providers import them by these
    reference names).  No real paddle exists in this environment; refuse to
    shadow one if it ever does."""
    if "paddle" in sys.modules and not getattr(
        sys.modules["paddle"], "__paddle_tpu_shim__", False
    ):
        raise RuntimeError("a real `paddle` package is importable; refusing to shim")
    import paddle_tpu.data_provider as pdp2

    paddle_mod = sys.modules.get("paddle")
    if paddle_mod is None:
        paddle_mod = types.ModuleType("paddle")
        paddle_mod.__paddle_tpu_shim__ = True
        sys.modules["paddle"] = paddle_mod
    trainer_mod = types.ModuleType("paddle.trainer")
    trainer_mod.PyDataProvider2 = pdp2
    sys.modules["paddle.trainer"] = trainer_mod
    sys.modules["paddle.trainer.PyDataProvider2"] = pdp2
    sys.modules["paddle.trainer_config_helpers"] = _helpers
    paddle_mod.trainer = trainer_mod
    paddle_mod.trainer_config_helpers = _helpers


@dataclasses.dataclass
class ParsedConfig:
    topology: Topology
    settings: TrainerSettings
    data_sources: Optional[DataSources]
    input_layers: List[str]
    output_layers: List[str]
    evaluators: List = dataclasses.field(default_factory=list)
    provider_input_types: Optional[dict] = None  # name -> InputType (if resolved)
    # Default feeding map {layer_name: index_in_sample_tuple}.  Non-None only
    # when slot binding had to PERMUTE provider slots onto data layers (the
    # unique-assignment path in _bind_slots): provider tuples stay in slot
    # order, so the trainer must pair them through this map — pass it as
    # DataFeeder's ``feeding``.  None ⇒ identity (positional) feeding.
    feeding: Optional[dict] = None
    # old-face TrainData/TestData declarations (config_parser.py:1115)
    train_data: Optional[object] = None
    test_data: Optional[object] = None
    # provenance + parse-level context for the graph linter
    # (analysis.graph_lint.lint_parsed): the config file that built this
    # topology, and EVERY layer name the config created — including ones
    # that never reached an output (dead-layer rule G005)
    source_file: Optional[str] = None
    all_layer_names: List[str] = dataclasses.field(default_factory=list)

    def serialize(self) -> str:
        return self.topology.serialize()


def _parse_config_args(config_arg_str: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for piece in (config_arg_str or "").split(","):
        piece = piece.strip()
        if piece:
            k, _, v = piece.partition("=")
            out[k.strip()] = v.strip()
    return out


def _read_file_list(list_path: Optional[str], config_dir: str) -> list:
    """Entries of a train/test .list file (one data path per line), resolved
    like the reference trainer does — relative to the run directory."""
    if not list_path:
        return []
    p = list_path if os.path.isabs(list_path) else os.path.join(config_dir, list_path)
    if not os.path.exists(p):
        return []
    with open(p) as f:
        return [ln.strip() for ln in f if ln.strip()]


def _infer_slot_type(value, size: int):
    """Infer a slot's InputType from one sample value + the data layer's
    declared size (the first-batch introspection fallback; the reference
    always gets types from the provider object — PyDataProvider2.cpp:54-69 —
    so this only covers providers whose hook ran but declared nothing).
    Returns None when the value shape is ambiguous."""
    import numpy as _np

    from paddle_tpu.core import data_types as _dt

    if isinstance(value, (int, _np.integer)):
        return _dt.integer_value(size)
    if isinstance(value, (float, _np.floating)):
        return _dt.dense_vector(1) if size == 1 else None
    if isinstance(value, _np.ndarray):
        if value.ndim == 1 and value.size == size:
            return _dt.dense_vector(size)
        if value.ndim == 2 and value.shape[1] == size:
            return _dt.dense_vector_sequence(size)
        return None
    if isinstance(value, (list, tuple)):
        if not value:
            return None
        first = value[0]
        if isinstance(first, (int, _np.integer)):
            # A list of ints is ALWAYS an id sequence in v1 providers —
            # dense values come as floats/ndarrays (PyDataProvider2.cpp
            # slot types).  Never fall back to dense on len==size; that
            # coincidence mis-fed small-size configs and is first-sample-
            # dependent.
            if all(isinstance(v, (int, _np.integer)) for v in value):
                return _dt.integer_value_sequence(size)
            return None
        if isinstance(first, (float, _np.floating)):
            return _dt.dense_vector(size) if len(value) == size else None
        if isinstance(first, (list, tuple, _np.ndarray)):
            if (
                first
                and isinstance(first, (list, tuple))
                and len(first) == 2
                and isinstance(first[0], (int, _np.integer))
                and isinstance(first[1], (float, _np.floating))
            ):
                return _dt.sparse_float_vector(size)
            inner = [len(v) for v in value]
            if all(n == size for n in inner):
                return _dt.dense_vector_sequence(size)
            return None
    return None


def _resolve_data_path(p: str, config_dir: str) -> Optional[str]:
    """Reference data paths are relative to the RUN directory (the trainer
    is launched from the source root: ``trainer/tests/mnist.list``), not the
    config file — try the config dir, then each ancestor, then the bare
    basename next to the config."""
    if os.path.isabs(p):
        return p if os.path.exists(p) else None
    cands = [p, os.path.join(config_dir, p)]
    d = config_dir
    for _ in range(4):
        d = os.path.dirname(d) or "/"
        cands.append(os.path.join(d, p))
    cands.append(os.path.join(config_dir, os.path.basename(p)))
    for c in cands:
        if os.path.exists(c):
            return c
    return None


def _proto_data_files(dc, config_dir: str) -> list:
    """Expand a ProtoData files= declaration (a .list file of data paths, or
    a direct data path) into existing absolute paths."""
    if not dc or not dc.files:
        return []
    lst = _resolve_data_path(dc.files, config_dir)
    if lst is None:
        return []
    if lst.endswith(".list") or lst.endswith(".txt"):
        with open(lst) as f:
            entries = [ln.strip() for ln in f if ln.strip()]
        out = []
        for e in entries:
            r = _resolve_data_path(e, config_dir) or _resolve_data_path(
                e, os.path.dirname(lst)
            )
            if r:
                out.append(r)
        return out
    return [lst]


def _bind_and_assign_slot_types(
    parsed: ParsedConfig, itypes, label: str
) -> bool:
    """Shared tail of every old-face type resolver: positional/unique-bind
    the slot types to the data layers (recording a feeding permutation when
    one fires), assign them onto the frozen confs, and populate
    provider_input_types.  A bind failure marks the slots unresolved (the
    topology must stay buildable; the error surfaces at feed time) and
    still returns True — the declaration WAS handled."""
    data_confs = list(parsed.topology.data_layers().values())
    try:
        aligned, feeding = _bind_slots(itypes, data_confs, label)
        if feeding is not None:
            parsed.feeding = feeding
    except ValueError as e:
        _mark_unresolved_msg(parsed, str(e))
        return True
    resolved = {}
    for conf, t in zip(data_confs, aligned):
        if t is not None and conf.attrs.get("_v1_size_only"):
            object.__setattr__(conf, "input_type", t)
            conf.attrs.pop("_v1_size_only", None)
            resolved[conf.name] = t
    parsed.provider_input_types = resolved
    return True


def _simple_sample_dim(dc) -> int:
    """SimpleData's per-sample feature width: feat_dim * (2*context_len + 1)
    (SimpleDataProviderBase ctor, DataProvider.cpp:223)."""
    return int(dc.feat_dim) * (2 * int(dc.context_len or 0) + 1)


def _resolve_simple_data_types(parsed: ParsedConfig, config_dir: str) -> bool:
    """Old-face ``TrainData(SimpleData(files=...))`` (the reference's
    text-format provider, DataProvider.cpp SimpleDataProvider::loadDataFile:
    each line is ``label feat_1 .. feat_sampleDim``): one dense slot of
    sample_dim plus an integer label slot."""
    td = parsed.train_data
    if td is None or getattr(td, "kind", None) != "simple":
        return False
    if td.feat_dim is None:
        _mark_unresolved_msg(parsed, "SimpleData declares no feat_dim")
        return True
    from paddle_tpu.core.data_types import dense_vector, integer_value

    dim = _simple_sample_dim(td)
    return _bind_and_assign_slot_types(
        parsed, [dense_vector(dim), integer_value(1)],
        f"SimpleData({td.files})",
    )


def make_simple_data_reader(
    parsed: ParsedConfig, config_dir: str, train: bool = True
):
    """Reader over a SimpleData text declaration: yields
    ``(feats float32[sample_dim], int label)`` rows exactly as
    SimpleDataProvider::loadDataFile parses them."""
    import numpy as _np

    dc = parsed.train_data if train else (parsed.test_data or parsed.train_data)
    files = _proto_data_files(dc, config_dir)  # same .list/.txt expansion
    if not files:
        raise FileNotFoundError(
            f"SimpleData files {dc.files!r} not found under {config_dir}"
        )
    dim = _simple_sample_dim(dc)

    def reader():
        for path in files:
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    if len(parts) != dim + 1:
                        raise ValueError(
                            f"{path}: expected label + {dim} feats per "
                            f"line, got {len(parts)} fields"
                        )
                    yield (
                        _np.asarray(parts[1:], _np.float32),
                        int(parts[0]),
                    )

    if train:
        # SimpleDataProviderBase::reset shuffles every pass
        # (DataProvider.cpp fillBuffer -> shuffle); a label-sorted text
        # file must not train in single-class batches
        from paddle_tpu.reader.decorator import shuffle as _shuffle

        return _shuffle(reader, 65536)
    return reader


def _resolve_proto_data_types(parsed: ParsedConfig, config_dir: str) -> bool:
    """Old-face ``TrainData(ProtoData(files=...))``: the binary data's OWN
    DataHeader is the authoritative slot-type source
    (ProtoDataProvider.cpp:84 checkDataHeader) — read it and bind the slots
    to the data layers in feeding order.  Returns True when handled."""
    td = parsed.train_data
    if td is None or getattr(td, "kind", None) != "proto":
        return False
    files = _proto_data_files(td, config_dir)
    if not files:
        _mark_unresolved_msg(
            parsed, f"proto data files {td.files!r} not found under {config_dir}"
        )
        return True
    from paddle_tpu.io.protodata import read_proto_header, slot_input_types

    defs = read_proto_header(files[0])
    sequence = (getattr(td, "type", None) or "").endswith("sequence")
    try:
        itypes = slot_input_types(defs, sequence=sequence)
    except ValueError as e:
        _mark_unresolved_msg(parsed, str(e))
        return True
    return _bind_and_assign_slot_types(
        parsed, itypes, f"ProtoData({td.files})"
    )


def make_data_reader(
    parsed: ParsedConfig,
    config_dir: str,
    train: bool = True,
    shuffle: bool = True,
):
    """Reader over a parsed config's old-face data declaration (currently
    the ProtoData binary format; py/simple providers feed through
    define_py_data_sources2 instead).  Returns a v2-style reader callable
    yielding sample tuples in the config's feeding order.

    shuffle=True matches ProtoDataProvider::reset, which shuffles every
    pass unless skip_shuffle (ProtoDataProvider.cpp:372-379) — the
    checked-in mnist_bin_part is label-SORTED, so unshuffled training
    oscillates exactly as single-class batches would."""
    dc = parsed.train_data if train else parsed.test_data
    if dc is None or getattr(dc, "kind", None) != "proto":
        raise ValueError(
            "make_data_reader supports TrainData(ProtoData(...)) configs; "
            f"got {dc!r}"
        )
    files = _proto_data_files(dc, config_dir)
    if not files:
        raise FileNotFoundError(
            f"proto data files {dc.files!r} not found under {config_dir}"
        )
    from paddle_tpu.io.protodata import make_reader

    sequence = (getattr(dc, "type", None) or "").endswith("sequence")
    rd = make_reader(files, sequence=sequence)
    if shuffle and not train:
        shuffle = False  # test data is read in order (reference skipShuffle)
    if shuffle:
        from paddle_tpu.reader.decorator import shuffle as _shuffle

        # whole-dataset buffer: the reference loads all records into memory
        # and permutes sequence ids (loadDataAll + shuffledSequenceIds_)
        rd = _shuffle(rd, 65536)
    return rd


def _load_provider_module(module_name: str, config_dir: str):
    """Import a data-provider module for a config.  Loads by file path under
    a config-dir-unique module name: different demo dirs reuse the same
    provider module name (e.g. "dataprovider"), and importlib.import_module
    would hand the second config the first one's cached module — wrong input
    types, silently."""
    mod_path = os.path.join(config_dir, module_name + ".py")
    sys.path.insert(0, config_dir)  # provider's own sibling imports
    try:
        with _py2_shims():
            if os.path.exists(mod_path):
                # cache key: path + mtime — one CLI run touches the module
                # three times (type resolution + train reader + test reader)
                # and real providers do heavy module-level work (dict loads);
                # an edited file gets a new mtime, so staleness is bounded
                # to one exec per file version, and a FAILED exec is never
                # cached (the entry is dropped on the way out)
                mtime = int(os.stat(mod_path).st_mtime_ns)
                uniq = (
                    f"_v1_provider_{abs(hash(os.path.abspath(mod_path)))}"
                    f"_{mtime}_{module_name}"
                )
                if uniq in sys.modules:
                    return sys.modules[uniq]
                spec = importlib.util.spec_from_file_location(uniq, mod_path)
                mod = importlib.util.module_from_spec(spec)
                # py2-era provider files (reference demos predate python 3)
                mod.xrange = range
                mod.unicode = str
                sys.modules[uniq] = mod
                try:
                    spec.loader.exec_module(mod)
                except BaseException:
                    sys.modules.pop(uniq, None)
                    raise
                _py2_patch_siblings(config_dir)
                return mod
            mod = importlib.import_module(module_name)
            _py2_patch_siblings(config_dir)
            return mod
    finally:
        sys.path.pop(0)


def _py2_patch_siblings(config_dir: str) -> None:
    """Give py2-era helper modules the provider pulled in from the config
    dir (e.g. v1_api_demo/mnist/mnist_util.py: `for i in xrange(n)`) the
    same xrange/unicode aliases the provider module itself gets — their
    generator bodies run at ITERATION time, long after the _py2_shims
    context has exited."""
    prefix = os.path.abspath(config_dir) + os.sep
    for mod in list(sys.modules.values()):
        f = getattr(mod, "__file__", None)
        if f and os.path.abspath(f).startswith(prefix):
            if not hasattr(mod, "xrange"):
                mod.xrange = range
            if not hasattr(mod, "unicode"):
                mod.unicode = str


def make_provider_reader(
    parsed: ParsedConfig, config_dir: str, train: bool = True
):
    """Reader over a config's ``define_py_data_sources2`` declaration: import
    the provider module and call its @provider factory with the train/test
    file list + declared args — what the reference trainer does through
    PyDataProvider2.cpp:665 (embed CPython, call the decorated object).
    Returns a v2-style reader callable yielding sample tuples."""
    ds = parsed.data_sources
    if ds is None or not ds.module:
        raise ValueError(
            "config declares no define_py_data_sources2 provider"
        )
    module = ds.module if train else (ds.test_module or ds.module)
    obj_name = ds.obj if train else (ds.test_obj or ds.obj)
    mod = _load_provider_module(module, config_dir)
    obj = getattr(mod, obj_name, None)
    if obj is None:
        raise ValueError(
            f"provider module {module!r} has no object {obj_name!r}"
        )
    list_path = ds.train_list if train else ds.test_list
    files = _read_file_list(list_path, config_dir)
    # list entries are run-dir-relative in the reference; resolve against the
    # config dir when the cwd doesn't have them so configs run from anywhere
    files = [
        f
        if os.path.isabs(f) or os.path.exists(f)
        else os.path.join(config_dir, f)
        for f in files
    ]
    with _in_dir(config_dir), _py2_shims():
        rd = obj(*files, is_train=train, **(ds.args or {}))
    return rd


def make_config_reader(
    parsed: ParsedConfig, config_dir: str, train: bool = True
):
    """One entry point over both data planes: old-face
    ``TrainData(ProtoData(...))`` binary files and
    ``define_py_data_sources2`` python providers.  The CLI trainer feeds
    from this."""
    dc = parsed.train_data if train else parsed.test_data
    if dc is not None and getattr(dc, "kind", None) == "proto":
        return make_data_reader(parsed, config_dir, train=train)
    if dc is not None and getattr(dc, "kind", None) == "simple":
        return make_simple_data_reader(parsed, config_dir, train=train)
    return make_provider_reader(parsed, config_dir, train=train)


def make_batched_reader(
    parsed: ParsedConfig, config_dir: str, batch_size: int, train: bool = True
):
    """Sample reader → minibatch reader for a parsed v1 config, honoring the
    bucketing flags: with ``use_bucketing`` on, variable-length samples route
    through :func:`reader.bucketing.token_budget_batch` (token budget =
    ``bucketing_token_budget`` flag, else derived from ``batch_size`` × the
    first window's tallest ladder rung) so reference configs opt into
    length-bucketed feeding WITHOUT any config edits — the trainer's
    DataFeeder pads the emitted batches to the same shape ladder (SGD reads
    the flag too).  Flag off: plain ``paddle.batch`` semantics."""
    rd = make_config_reader(parsed, config_dir, train=train)
    from paddle_tpu.utils.flags import get_flag

    if not get_flag("use_bucketing"):
        from paddle_tpu import minibatch

        return minibatch.batch(rd, batch_size)
    from paddle_tpu.reader.bucketing import token_budget_batch

    budget = get_flag("bucketing_token_budget") or None
    return token_budget_batch(
        rd, token_budget=budget, batch_size=batch_size
    )


def _mark_unresolved_msg(parsed: ParsedConfig, reason: str) -> None:
    for c in parsed.topology.data_layers().values():
        if c.attrs.get("_v1_size_only"):
            c.attrs["_v1_unresolved"] = f"slot types unknown: {reason}"


def _slot_compatible(t, conf) -> bool:
    """Does slot type ``t`` dim-check against data layer ``conf``?  Dense and
    sparse slots must match the declared layer size exactly; index slots are
    compatible with any size — reference providers routinely declare
    ``integer_value(1)`` for a 1000-class label (benchmark provider.py
    initHook), so the value range carries no binding signal."""
    from paddle_tpu.core.data_types import SlotKind

    if t is None:
        return False
    if t.kind == SlotKind.INDEX:
        return True
    return t.dim == conf.size


def _bind_slots(itypes, data_confs, label: str):
    """Bind positional provider slot types to data layers, validating dims.

    Positional order is the contract (reference config_parser.py:205-222),
    but providers written against the DFS input order break silently if the
    orders ever diverge — so every binding is dim-checked, and when the
    positional binding fails the check we search for the assignment that
    does dim-check.  A unique consistent assignment is used (with a
    warning); none or several → hard error, never a silent mis-feed.
    Returns ``(aligned, feeding)``: a list of types aligned with
    ``data_confs`` plus a ``{layer_name: sample_index}`` feeding map —
    ``None`` for the identity (positional) binding.  The feeding map is NOT
    optional information when present: sample tuples stay in provider slot
    order, so a permuted binding that is not also fed through this map would
    deliver every value to the wrong layer (the types were re-aligned, the
    data wasn't)."""
    n = len(data_confs)
    if len(itypes) != n:
        raise ValueError(
            f"{label}: provider declares {len(itypes)} slots but the config "
            f"has {n} data layers "
            f"({[c.name for c in data_confs]})"
        )
    if all(_slot_compatible(t, c) for t, c in zip(itypes, data_confs)):
        return list(itypes), None
    # positional binding fails the dim check: search assignments over the
    # slot×layer candidate matrix
    cand = [
        [t if _slot_compatible(t, c) else None for c in data_confs]
        for t in itypes
    ]
    res = _unique_assignment(cand, n)
    if res is not None:
        out, assign = res
        # assign[slot_i] = layer_j  ⇒  layer_j reads sample index slot_i
        feeding = {
            data_confs[j].name: i for i, j in enumerate(assign)
        }
        if all(i == j for i, j in enumerate(assign)):
            feeding = None  # distinct mapping happens to be positional
        warnings.warn(
            f"{label}: provider slot types do not dim-check against the "
            f"data layers in feeding order "
            f"({[c.name for c in data_confs]}); using the unique "
            "dim-consistent assignment instead"
            + (f" with feeding map {feeding}" if feeding else ""),
            stacklevel=2,
        )
        return out, feeding
    raise ValueError(
        f"{label}: cannot bind provider slot types {itypes} to data layers "
        f"{[(c.name, c.size) for c in data_confs]}: no unique dim-consistent "
        "assignment exists.  Declare input_types in feeding order "
        "(Inputs(...) order if set, else DFS order from the outputs) or fix "
        "the slot dims."
    )


def _unique_assignment(cand, n: int):
    """Perfect matching over ``cand[slot][layer]`` (None = incompatible).
    Returns the layer-aligned type list when exactly one DISTINCT
    layer→type mapping exists (identical types swapping slots count as the
    same mapping), else None.  The search dedups into distinct mappings as
    it goes and stops only once TWO exist — capping raw solution count
    instead would declare ambiguous bindings unique whenever the first
    branch alone yields many permutations of equal types."""
    distinct: set = set()
    first_sol: list = []
    budget = [200_000]  # node guard: factorial worst case bails to "no
    # unique assignment" (the hard-error path), never to a wrong binding

    def search(i: int, used: int, assign: list) -> None:
        if len(distinct) > 1 or budget[0] <= 0:
            return
        budget[0] -= 1
        if i == n:
            key = tuple(
                sorted((j, repr(cand[i2][j])) for i2, j in enumerate(assign))
            )
            if key not in distinct:
                distinct.add(key)
                if len(distinct) == 1:
                    first_sol[:] = assign
            return
        for j in range(n):
            if cand[i][j] is not None and not used & (1 << j):
                assign.append(j)
                search(i + 1, used | (1 << j), assign)
                assign.pop()

    search(0, 0, [])
    if len(distinct) != 1 or budget[0] <= 0:  # exhausted => possibly ambiguous
        return None
    out = [None] * n
    for i, j in enumerate(first_sol):
        out[j] = cand[i][j]
    return out, list(first_sol)


def _first_sample(obj, ds, config_dir: str):
    """One sample from the provider, shuffle disabled (is_train=False keeps
    the pool from buffering 1024 samples before the first yield)."""
    files = _read_file_list(ds.train_list, config_dir)
    rd = obj(*files, is_train=False, **(ds.args or {}))
    return next(iter(rd()))


def _resolve_provider_types(parsed: ParsedConfig, config_dir: str) -> None:
    """Import the declared provider module and patch data-layer InputTypes
    from the provider object itself: its @provider(input_types=...)
    declaration, else its init_hook run with the config's real args + file
    list (reference PyDataProvider2.cpp:665 embeds CPython and reads
    input_types after init_hook), else first-batch introspection.  Slots
    still unresolved are marked so feeding raises instead of silently using
    a dense placeholder."""
    if _resolve_proto_data_types(parsed, config_dir):
        return
    if _resolve_simple_data_types(parsed, config_dir):
        return
    ds = parsed.data_sources
    if ds is None or not ds.module:
        return
    try:
        mod = _load_provider_module(ds.module, config_dir)
    except ImportError as e:
        _mark_unresolved(parsed, ds, f"provider module import failed: {e!r}")
        return
    obj = getattr(mod, ds.obj, None)
    itypes = getattr(obj, "input_types", None)
    names = getattr(obj, "slot_names", None)
    hook_error: Optional[BaseException] = None
    if itypes is None and hasattr(obj, "resolve_input_types"):
        # hook-declared types (reference initializer pattern); hooks open
        # data files relative to the config/run dir, so resolve from there
        try:
            with _in_dir(config_dir), _py2_shims():
                itypes, names = obj.resolve_input_types(
                    file_list=_read_file_list(ds.train_list, config_dir),
                    **(ds.args or {}),
                )
        except Exception as e:
            hook_error = e
            itypes = None
    data_confs = list(parsed.topology.data_layers().values())
    if itypes is None and obj is not None:
        # last resort: pull one real sample and infer each slot's type from
        # its value + the data layer's declared size
        try:
            with _in_dir(config_dir), _py2_shims():
                sample = _first_sample(obj, ds, config_dir)
        except Exception as e:
            hook_error = hook_error or e
            sample = None
        if sample is not None and not isinstance(sample, (list, tuple)):
            sample = (sample,)
        if sample is not None and len(sample) == len(data_confs):
            # infer each value against each layer's size and take the
            # unique dim-consistent assignment (positional when it checks;
            # robust to provider-yield vs feeding-order divergence)
            cand = [
                [_infer_slot_type(v, c.size) for c in data_confs]
                for v in sample
            ]
            n = len(data_confs)
            positional = [cand[i][i] for i in range(n)]
            if all(t is not None for t in positional):
                aligned = positional
            else:
                res = _unique_assignment(cand, n)
                if res is None:
                    aligned = None
                else:
                    aligned, assign = res
                    if any(i != j for i, j in enumerate(assign)):
                        # permuted binding: feed tuples through the map
                        parsed.feeding = {
                            data_confs[j].name: i
                            for i, j in enumerate(assign)
                        }
            if aligned is not None:
                itypes, names = aligned, [c.name for c in data_confs]
    if itypes is None:
        _mark_unresolved(
            parsed,
            ds,
            f"init_hook/introspection failed: {hook_error!r}"
            if hook_error
            else "provider declares no input_types",
        )
        return
    label = f"{ds.module}.{ds.obj}"
    if names:
        by_name = dict(zip(names, itypes))
        aligned = [by_name.get(c.name) for c in data_confs]
        bad = [
            (c.name, c.size, t)
            for c, t in zip(data_confs, aligned)
            if t is not None and not _slot_compatible(t, c)
        ]
        if bad:
            raise ValueError(
                f"{label}: named slot types do not dim-check against their "
                f"data layers: {bad}"
            )
        # Sample tuples arrive in the provider's slot-NAME order; when that
        # differs from feeding order the tuples must be re-paired by name.
        name_pos = {nm: i for i, nm in enumerate(names)}
        if any(
            name_pos.get(c.name, j) != j for j, c in enumerate(data_confs)
        ):
            parsed.feeding = {
                c.name: name_pos[c.name]
                for c in data_confs
                if c.name in name_pos
            }
    else:
        # Positional provider types pair with data layers in FEEDING order
        # (Inputs()/DFS — see Topology.data_layers), validated against each
        # layer's declared size; mismatch → unique re-assignment or error.
        aligned, feeding = _bind_slots(list(itypes), data_confs, label)
        if feeding is not None:
            parsed.feeding = feeding
    resolved = {}
    for conf, t in zip(data_confs, aligned):
        if t is not None and conf.attrs.get("_v1_size_only"):
            # LayerConf is frozen; parse-time resolution happens before any
            # compilation, so this is the one sanctioned mutation point.
            object.__setattr__(conf, "input_type", t)
            conf.attrs.pop("_v1_size_only", None)
            resolved[conf.name] = t
    parsed.provider_input_types = resolved


def _mark_unresolved(parsed: ParsedConfig, ds, reason: str) -> None:
    """Provider types could not be resolved: leave the parse-time dense
    placeholders in place (building/inspecting the topology stays fine) but
    tag the slots so data_types()/feeding raises a hard error instead of
    silently feeding index/sequence slots as dense vectors."""
    for c in parsed.topology.data_layers().values():
        if c.attrs.get("_v1_size_only"):
            c.attrs["_v1_unresolved"] = (
                f"slot types unknown: provider {ds.module}.{ds.obj} — {reason}"
            )


import contextlib

# os.chdir is process-global.  This lock serializes the PARSE-TIME chdirs
# in this module against each other (concurrent parse_config calls); it
# cannot protect arbitrary other threads that read the cwd (e.g. a
# background feeder resolving relative paths mid-parse) — those windows are
# only narrowed by keeping each chdir scope as short as possible.  Provider
# code that must be robust should open paths relative to its own __file__.
from paddle_tpu.analysis.lock_sanitizer import make_rlock

_chdir_lock = make_rlock("v1_compat._chdir_lock")


@contextlib.contextmanager
def _in_dir(d: str):
    with _chdir_lock:
        cwd = os.getcwd()
        os.chdir(d)
        try:
            yield
        finally:
            os.chdir(cwd)


@contextlib.contextmanager
def _py2_shims():
    """Module-level py2 attributes the reference-era configs/providers touch
    (sys.maxint in init hooks, string.letters in tokenizers), installed only
    for the duration of a config exec / provider import."""
    import string

    added = []
    if not hasattr(sys, "maxint"):
        sys.maxint = sys.maxsize
        added.append((sys, "maxint"))
    if not hasattr(string, "letters"):
        string.letters = string.ascii_letters
        added.append((string, "letters"))
    try:
        yield
    finally:
        for mod, attr in added:
            delattr(mod, attr)


def parse_config(config, config_arg_str: str = "") -> ParsedConfig:
    """Execute a v1 trainer-config python file — or CALL a config function
    (the reference parse_config accepts both, config_parser.py:3669) — and
    return the build result (reference returns the proto; here the typed
    Topology + settings)."""
    _install_import_shims()
    from paddle_tpu.core.topology import reset_auto_names

    reset_auto_names()
    is_callable = callable(config)
    config_file = None if is_callable else config
    config_dir = (
        os.getcwd()
        if is_callable
        else os.path.dirname(os.path.abspath(config_file)) or "."
    )
    from paddle_tpu.core.topology import set_layer_sink

    state = _helpers._ParseState(_parse_config_args(config_arg_str))
    prev_state = _helpers._state
    _helpers._state = state
    prev_sink = set_layer_sink(
        lambda lo: state.all_layers.__setitem__(lo.conf.name, lo)
    )
    sys.path.insert(0, config_dir)
    try:
        with _py2_shims():
            if is_callable:
                config()
            else:
                with open(config_file) as f:
                    src = f.read()
                # Pre-populate the namespace with the full helper surface —
                # the reference execs configs inside config_parser's own
                # namespace, so old-face .conf files use Layer/TrainData/
                # Settings/default_* WITHOUT any import.
                ns = {
                    k: v
                    for k, v in vars(_helpers).items()
                    if not k.startswith("_")
                }
                ns.update({
                    "__file__": os.path.abspath(config_file),
                    "__name__": "__paddle_config__",
                    # py2-era configs: reference v1 configs predate python 3
                    "xrange": range,
                    "unicode": str,
                })
                exec(compile(src, config_file, "exec"), ns)
    finally:
        sys.path.pop(0)
        _helpers._state = prev_state
        # a config that died inside RecurrentLayerGroupBegin/End must not
        # leave the raw-group trace open for the next parse.  Unwind it
        # BEFORE restoring the sink: the trace context's own exit restores
        # the sink that was active when the group opened (this parse's),
        # which would clobber the restoration below if ordered after it.
        from paddle_tpu.v1_compat.raw_face import reset_raw_state

        reset_raw_state()
        set_layer_sink(prev_sink)

    label = config_file or getattr(config, "__name__", "<callable config>")
    if state.submodel_stack:
        raise ValueError(f"{label}: SubModelBegin without matching SubModelEnd")
    if state.model_type_name == "multi_nn" and state.submodels:
        _assemble_multi_nn(state, label)
    if state.pending_output_names:  # capital-O Outputs(name, ...) form
        # reference alias: the beam-search generator registers its predict
        # layer as __beam_search_predict__ (config_parser) — map it to the
        # beam_search layer built during the exec, or to the OUTER
        # recurrent_group wrapping it (nested generation: the reference
        # concatenates per-subsequence beam results through the group,
        # sample_trainer_nest_rnn_gen.conf)
        if "__beam_search_predict__" in state.pending_output_names:
            gen_groups = [
                lo for lo in state.all_layers.values()
                if lo.conf.type == "recurrent_group"
                and any(
                    c.type == "beam_search"
                    for c in lo.conf.attrs["_sub_topology"].layers.values()
                )
            ]
            beams = [
                lo for lo in state.all_layers.values()
                if lo.conf.type == "beam_search"
            ]
            if len(gen_groups) == 1:
                state.all_layers["__beam_search_predict__"] = gen_groups[0]
            elif len(beams) == 1:
                state.all_layers["__beam_search_predict__"] = beams[0]
        missing = [n for n in state.pending_output_names if n not in state.all_layers]
        if missing:
            raise KeyError(
                f"{label}: Outputs() names {missing} were never built"
            )
        state.outputs.extend(
            state.all_layers[n] for n in state.pending_output_names
        )
    assert state.outputs, f"{label}: config declared no outputs()"
    topo = Topology(list(state.outputs))
    # Explicit Inputs(...) / inputs(...) pins the feeding order (reference
    # config_parser.py:205-222: "The data streams from DataProvider must
    # have the same order").  Without it data_layers() uses DFS order, the
    # same order the reference's outputs() computes via __dfs_travel__.
    explicit_inputs = (
        [l.name for l in state.inputs] if state.inputs else list(state.input_names)
    )
    if explicit_inputs and all(
        n in topo.layers and topo.layers[n].type == "data" for n in explicit_inputs
    ):
        # pin only a COMPLETE ordering: a partial Inputs() list must not
        # shrink the feed contract (data_layers() returns input_order
        # verbatim — a missing slot would silently vanish from feeding)
        all_data = {
            n for n, c in topo.layers.items() if c.type == "data"
        }
        if set(explicit_inputs) == all_data:
            topo.input_order = tuple(explicit_inputs)
        else:
            warnings.warn(
                f"{label}: Inputs({explicit_inputs}) does not cover every "
                f"data layer ({sorted(all_data)}); falling back to DFS "
                "feeding order",
                stacklevel=2,
            )
    parsed = ParsedConfig(
        topology=topo,
        settings=state.settings,
        data_sources=state.data_sources,
        train_data=state.train_data,
        test_data=state.test_data,
        input_layers=(
            [l.name for l in state.inputs]
            if state.inputs
            else list(state.input_names)  # capital-I Inputs(name, ...) form
        ),
        output_layers=[l.name for l in state.outputs],
        evaluators=list(state.evaluators),
        source_file=config_file,
        all_layer_names=list(state.all_layers),
    )
    _resolve_provider_types(parsed, config_dir)
    return parsed


def _assemble_multi_nn(state, label: str) -> None:
    """model_type('multi_nn') ensembles (reference MultiNetwork.cpp,
    ModelConfig.proto:579): each SubModelBegin/End block declared its own
    Inputs/Outputs; the whole ensemble compiles into ONE jitted program
    whose training objective is the summed sub-network cost (multi_nn_cost
    layer — the reference sums all of MultiNetwork::forward's concatenated
    outArgs).  Feeding order = the sub-models' Inputs() concatenated in
    declaration order (the reference splits inArgs by dataId per
    sub-network, MultiNetwork.cpp:70)."""
    from paddle_tpu.core.topology import LayerConf as _LC, LayerOutput as _LO

    sub_outs: List[LayerOutput] = []
    for sm in state.submodels:
        if not sm["outputs"]:
            raise ValueError(
                f"{label}: multi_nn submodel {sm['name']!r} declares no Outputs"
            )
        for n in sm["outputs"]:
            if n not in state.all_layers:
                raise KeyError(
                    f"{label}: submodel {sm['name']!r} output {n!r} was "
                    "never built"
                )
            sub_outs.append(state.all_layers[n])
    joint = _LO(
        _LC(
            name="__multi_nn_cost__",
            type="multi_nn_cost",
            size=1,
            inputs=tuple(o.name for o in sub_outs),
            bias=False,
        ),
        sub_outs,
    )
    state.outputs = [joint] + sub_outs
    state.pending_output_names = []
    if not state.input_names:
        state.input_names = [n for sm in state.submodels for n in sm["inputs"]]


def make_optimizer(settings: TrainerSettings):
    """Map settings() onto a paddle_tpu optimizer instance (the v2
    update_equation)."""
    import paddle_tpu.optimizer as O

    method = settings.learning_method
    kind = getattr(method, "kind", "sgd") if method is not None else "sgd"
    reg = settings.regularization
    if reg is not None:
        reg = (
            O.L1Regularization(reg.rate)
            if isinstance(reg, _helpers.L1Regularization)
            else O.L2Regularization(reg.rate)
        )
    avg = settings.model_average
    if avg is not None:
        avg = O.ModelAverage(average_window=avg.average_window)
    common = dict(
        learning_rate=settings.learning_rate,
        learning_rate_schedule=settings.learning_rate_schedule,
        learning_rate_decay_a=settings.learning_rate_decay_a,
        learning_rate_decay_b=settings.learning_rate_decay_b,
        learning_rate_args=getattr(settings, "learning_rate_args", ""),
        regularization=reg,
        gradient_clipping_threshold=settings.gradient_clipping_threshold or 0.0,
        model_average=avg,
        # 'manual' boundaries are numSamplesProcessed in the reference;
        # the step counter converts through the config's batch size
        samples_per_step=float(settings.batch_size or 1),
    )
    extra = dict(getattr(method, "extra", {}))
    cls = {
        "sgd": O.Momentum,
        "momentum": O.Momentum,
        "adam": O.Adam,
        "adamax": O.AdaMax,
        "adagrad": O.AdaGrad,
        "decayed_adagrad": O.DecayedAdaGrad,
        "adadelta": O.AdaDelta,
        "rmsprop": O.RMSProp,
    }[kind]
    if cls is O.Momentum and "momentum" not in extra and kind == "sgd":
        extra["momentum"] = 0.0
    if cls is O.Adam:
        extra = {
            "beta1": extra.get("beta1", 0.9),
            "beta2": extra.get("beta2", 0.999),
            "epsilon": extra.get("epsilon", 1e-8),
        }
    return cls(**extra, **common)
