"""v1 config-file compatibility — run reference-era trainer configs
unmodified (north star: v1_api_demo configs run on TPU).

``parse_config(path, config_arg_str)`` mirrors the reference entry
(python/paddle/trainer/config_parser.py:3669 parse_config): it installs
``paddle.trainer_config_helpers`` / ``paddle.trainer.PyDataProvider2`` import
shims, executes the config file, and returns a :class:`ParsedConfig` holding
the built Topology, trainer settings, and data-source declarations — instead
of the reference's protobuf TrainerConfig.

Data-layer input types: v1 ``data_layer`` declares only a size; the real slot
types belong to the data provider (reference DataProvider2 ownership).  After
executing the config we import the declared provider module and resolve each
data layer's InputType from the @provider declaration, so feeding/training
work end to end.
"""

from __future__ import annotations

import dataclasses
import importlib
import importlib.util
import os
import sys
import types
import warnings
from typing import Dict, List, Optional

from paddle_tpu.core.topology import LayerConf, Topology

from paddle_tpu.v1_compat import config_helpers as _helpers
from paddle_tpu.v1_compat.config_helpers import (  # noqa: F401
    DataSources,
    TrainerSettings,
)

__all__ = ["parse_config", "ParsedConfig", "make_optimizer"]


def _install_import_shims() -> None:
    """Make ``paddle.trainer_config_helpers`` / ``paddle.trainer.
    PyDataProvider2`` importable (configs and providers import them by these
    reference names).  No real paddle exists in this environment; refuse to
    shadow one if it ever does."""
    if "paddle" in sys.modules and not getattr(
        sys.modules["paddle"], "__paddle_tpu_shim__", False
    ):
        raise RuntimeError("a real `paddle` package is importable; refusing to shim")
    import paddle_tpu.data_provider as pdp2

    paddle_mod = sys.modules.get("paddle")
    if paddle_mod is None:
        paddle_mod = types.ModuleType("paddle")
        paddle_mod.__paddle_tpu_shim__ = True
        sys.modules["paddle"] = paddle_mod
    trainer_mod = types.ModuleType("paddle.trainer")
    trainer_mod.PyDataProvider2 = pdp2
    sys.modules["paddle.trainer"] = trainer_mod
    sys.modules["paddle.trainer.PyDataProvider2"] = pdp2
    sys.modules["paddle.trainer_config_helpers"] = _helpers
    paddle_mod.trainer = trainer_mod
    paddle_mod.trainer_config_helpers = _helpers


@dataclasses.dataclass
class ParsedConfig:
    topology: Topology
    settings: TrainerSettings
    data_sources: Optional[DataSources]
    input_layers: List[str]
    output_layers: List[str]
    evaluators: List = dataclasses.field(default_factory=list)
    provider_input_types: Optional[dict] = None  # name -> InputType (if resolved)
    # old-face TrainData/TestData declarations (config_parser.py:1115)
    train_data: Optional[object] = None
    test_data: Optional[object] = None

    def serialize(self) -> str:
        return self.topology.serialize()


def _parse_config_args(config_arg_str: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for piece in (config_arg_str or "").split(","):
        piece = piece.strip()
        if piece:
            k, _, v = piece.partition("=")
            out[k.strip()] = v.strip()
    return out


def _read_file_list(list_path: Optional[str], config_dir: str) -> list:
    """Entries of a train/test .list file (one data path per line), resolved
    like the reference trainer does — relative to the run directory."""
    if not list_path:
        return []
    p = list_path if os.path.isabs(list_path) else os.path.join(config_dir, list_path)
    if not os.path.exists(p):
        return []
    with open(p) as f:
        return [ln.strip() for ln in f if ln.strip()]


def _infer_slot_type(value, size: int):
    """Infer a slot's InputType from one sample value + the data layer's
    declared size (the first-batch introspection fallback; the reference
    always gets types from the provider object — PyDataProvider2.cpp:54-69 —
    so this only covers providers whose hook ran but declared nothing).
    Returns None when the value shape is ambiguous."""
    import numpy as _np

    from paddle_tpu.core import data_types as _dt

    if isinstance(value, (int, _np.integer)):
        return _dt.integer_value(size)
    if isinstance(value, (float, _np.floating)):
        return _dt.dense_vector(1) if size == 1 else None
    if isinstance(value, _np.ndarray):
        if value.ndim == 1 and value.size == size:
            return _dt.dense_vector(size)
        if value.ndim == 2 and value.shape[1] == size:
            return _dt.dense_vector_sequence(size)
        return None
    if isinstance(value, (list, tuple)):
        if not value:
            return None
        first = value[0]
        if isinstance(first, (int, _np.integer)):
            ints = all(isinstance(v, (int, _np.integer)) for v in value)
            if ints and len(value) != size:
                return _dt.integer_value_sequence(size)
            if len(value) == size:
                return _dt.dense_vector(size)
            return None
        if isinstance(first, (float, _np.floating)):
            return _dt.dense_vector(size) if len(value) == size else None
        if isinstance(first, (list, tuple, _np.ndarray)):
            if (
                first
                and isinstance(first, (list, tuple))
                and len(first) == 2
                and isinstance(first[0], (int, _np.integer))
                and isinstance(first[1], (float, _np.floating))
            ):
                return _dt.sparse_float_vector(size)
            inner = [len(v) for v in value]
            if all(n == size for n in inner):
                return _dt.dense_vector_sequence(size)
            return None
    return None


def _first_sample(obj, ds, config_dir: str):
    """One sample from the provider, shuffle disabled (is_train=False keeps
    the pool from buffering 1024 samples before the first yield)."""
    files = _read_file_list(ds.train_list, config_dir)
    rd = obj(*files, is_train=False, **(ds.args or {}))
    return next(iter(rd()))


def _resolve_provider_types(parsed: ParsedConfig, config_dir: str) -> None:
    """Import the declared provider module and patch data-layer InputTypes
    from the provider object itself: its @provider(input_types=...)
    declaration, else its init_hook run with the config's real args + file
    list (reference PyDataProvider2.cpp:665 embeds CPython and reads
    input_types after init_hook), else first-batch introspection.  Slots
    still unresolved are marked so feeding raises instead of silently using
    a dense placeholder."""
    ds = parsed.data_sources
    if ds is None or not ds.module:
        return
    # Load by file path under a config-dir-unique module name: different
    # demo dirs reuse the same provider module name (e.g. "dataprovider"),
    # and importlib.import_module would hand the second config the first
    # one's cached module — wrong input types, silently.
    mod_path = os.path.join(config_dir, ds.module + ".py")
    sys.path.insert(0, config_dir)  # provider's own sibling imports
    try:
        with _py2_shims():
            if os.path.exists(mod_path):
                uniq = f"_v1_provider_{abs(hash(os.path.abspath(mod_path)))}_{ds.module}"
                spec = importlib.util.spec_from_file_location(uniq, mod_path)
                mod = importlib.util.module_from_spec(spec)
                # py2-era provider files (reference demos predate python 3)
                mod.xrange = range
                mod.unicode = str
                sys.modules[uniq] = mod
                spec.loader.exec_module(mod)
            else:
                mod = importlib.import_module(ds.module)
    except ImportError as e:
        _mark_unresolved(parsed, ds, f"provider module import failed: {e!r}")
        return
    finally:
        sys.path.pop(0)
    obj = getattr(mod, ds.obj, None)
    itypes = getattr(obj, "input_types", None)
    names = getattr(obj, "slot_names", None)
    hook_error: Optional[BaseException] = None
    cwd = os.getcwd()
    if itypes is None and hasattr(obj, "resolve_input_types"):
        # hook-declared types (reference initializer pattern); hooks open
        # data files relative to the config/run dir, so resolve from there
        try:
            os.chdir(config_dir)
            with _py2_shims():
                itypes, names = obj.resolve_input_types(
                    file_list=_read_file_list(ds.train_list, config_dir),
                    **(ds.args or {}),
                )
        except Exception as e:
            hook_error = e
            itypes = None
        finally:
            os.chdir(cwd)
    data_confs = list(parsed.topology.data_layers().values())
    if itypes is None and obj is not None:
        # last resort: pull one real sample and infer each slot's type from
        # its value + the data layer's declared size
        try:
            os.chdir(config_dir)
            with _py2_shims():
                sample = _first_sample(obj, ds, config_dir)
        except Exception as e:
            hook_error = hook_error or e
            sample = None
        finally:
            os.chdir(cwd)
        if sample is not None:
            items = sample if isinstance(sample, (list, tuple)) else (sample,)
            inferred = [
                _infer_slot_type(v, c.size) for v, c in zip(items, data_confs)
            ]
            if len(items) == len(data_confs) and all(
                t is not None for t in inferred
            ):
                itypes, names = inferred, None
    if itypes is None:
        _mark_unresolved(
            parsed,
            ds,
            f"init_hook/introspection failed: {hook_error!r}"
            if hook_error
            else "provider declares no input_types",
        )
        return
    # Declaration order, NOT graph-traversal order — positional provider
    # types pair with data layers the way readers yield tuples.
    by_name = dict(zip(names, itypes)) if names else None
    resolved = {}
    for i, conf in enumerate(data_confs):
        if by_name is not None:
            t = by_name.get(conf.name)
        else:
            t = itypes[i] if i < len(itypes) else None
        if t is not None and conf.attrs.get("_v1_size_only"):
            # LayerConf is frozen; parse-time resolution happens before any
            # compilation, so this is the one sanctioned mutation point.
            object.__setattr__(conf, "input_type", t)
            conf.attrs.pop("_v1_size_only", None)
            resolved[conf.name] = t
    parsed.provider_input_types = resolved


def _mark_unresolved(parsed: ParsedConfig, ds, reason: str) -> None:
    """Provider types could not be resolved: leave the parse-time dense
    placeholders in place (building/inspecting the topology stays fine) but
    tag the slots so data_types()/feeding raises a hard error instead of
    silently feeding index/sequence slots as dense vectors."""
    for c in parsed.topology.data_layers().values():
        if c.attrs.get("_v1_size_only"):
            c.attrs["_v1_unresolved"] = (
                f"slot types unknown: provider {ds.module}.{ds.obj} — {reason}"
            )


import contextlib


@contextlib.contextmanager
def _py2_shims():
    """Module-level py2 attributes the reference-era configs/providers touch
    (sys.maxint in init hooks, string.letters in tokenizers), installed only
    for the duration of a config exec / provider import."""
    import string

    added = []
    if not hasattr(sys, "maxint"):
        sys.maxint = sys.maxsize
        added.append((sys, "maxint"))
    if not hasattr(string, "letters"):
        string.letters = string.ascii_letters
        added.append((string, "letters"))
    try:
        yield
    finally:
        for mod, attr in added:
            delattr(mod, attr)


def parse_config(config, config_arg_str: str = "") -> ParsedConfig:
    """Execute a v1 trainer-config python file — or CALL a config function
    (the reference parse_config accepts both, config_parser.py:3669) — and
    return the build result (reference returns the proto; here the typed
    Topology + settings)."""
    _install_import_shims()
    from paddle_tpu.core.topology import reset_auto_names

    reset_auto_names()
    is_callable = callable(config)
    config_file = None if is_callable else config
    config_dir = (
        os.getcwd()
        if is_callable
        else os.path.dirname(os.path.abspath(config_file)) or "."
    )
    from paddle_tpu.core.topology import set_layer_sink

    state = _helpers._ParseState(_parse_config_args(config_arg_str))
    prev_state = _helpers._state
    _helpers._state = state
    prev_sink = set_layer_sink(
        lambda lo: state.all_layers.__setitem__(lo.conf.name, lo)
    )
    sys.path.insert(0, config_dir)
    try:
        with _py2_shims():
            if is_callable:
                config()
            else:
                with open(config_file) as f:
                    src = f.read()
                # Pre-populate the namespace with the full helper surface —
                # the reference execs configs inside config_parser's own
                # namespace, so old-face .conf files use Layer/TrainData/
                # Settings/default_* WITHOUT any import.
                ns = {
                    k: v
                    for k, v in vars(_helpers).items()
                    if not k.startswith("_")
                }
                ns.update({
                    "__file__": os.path.abspath(config_file),
                    "__name__": "__paddle_config__",
                    # py2-era configs: reference v1 configs predate python 3
                    "xrange": range,
                    "unicode": str,
                })
                exec(compile(src, config_file, "exec"), ns)
    finally:
        sys.path.pop(0)
        _helpers._state = prev_state
        # a config that died inside RecurrentLayerGroupBegin/End must not
        # leave the raw-group trace open for the next parse.  Unwind it
        # BEFORE restoring the sink: the trace context's own exit restores
        # the sink that was active when the group opened (this parse's),
        # which would clobber the restoration below if ordered after it.
        from paddle_tpu.v1_compat.raw_face import reset_raw_state

        reset_raw_state()
        set_layer_sink(prev_sink)

    label = config_file or getattr(config, "__name__", "<callable config>")
    if state.pending_output_names:  # capital-O Outputs(name, ...) form
        # reference alias: the beam-search generator registers its predict
        # layer as __beam_search_predict__ (config_parser) — map it to the
        # beam_search layer built during the exec
        if "__beam_search_predict__" in state.pending_output_names:
            beams = [
                lo for lo in state.all_layers.values()
                if lo.conf.type == "beam_search"
            ]
            if len(beams) == 1:
                state.all_layers["__beam_search_predict__"] = beams[0]
        missing = [n for n in state.pending_output_names if n not in state.all_layers]
        if missing:
            raise KeyError(
                f"{label}: Outputs() names {missing} were never built"
            )
        state.outputs.extend(
            state.all_layers[n] for n in state.pending_output_names
        )
    assert state.outputs, f"{label}: config declared no outputs()"
    topo = Topology(list(state.outputs))
    parsed = ParsedConfig(
        topology=topo,
        settings=state.settings,
        data_sources=state.data_sources,
        train_data=state.train_data,
        test_data=state.test_data,
        input_layers=(
            [l.name for l in state.inputs]
            if state.inputs
            else list(state.input_names)  # capital-I Inputs(name, ...) form
        ),
        output_layers=[l.name for l in state.outputs],
        evaluators=list(state.evaluators),
    )
    _resolve_provider_types(parsed, config_dir)
    return parsed


def make_optimizer(settings: TrainerSettings):
    """Map settings() onto a paddle_tpu optimizer instance (the v2
    update_equation)."""
    import paddle_tpu.optimizer as O

    method = settings.learning_method
    kind = getattr(method, "kind", "sgd") if method is not None else "sgd"
    reg = settings.regularization
    if reg is not None:
        reg = (
            O.L1Regularization(reg.rate)
            if isinstance(reg, _helpers.L1Regularization)
            else O.L2Regularization(reg.rate)
        )
    avg = settings.model_average
    if avg is not None:
        avg = O.ModelAverage(average_window=avg.average_window)
    common = dict(
        learning_rate=settings.learning_rate,
        learning_rate_schedule=settings.learning_rate_schedule,
        learning_rate_decay_a=settings.learning_rate_decay_a,
        learning_rate_decay_b=settings.learning_rate_decay_b,
        regularization=reg,
        gradient_clipping_threshold=settings.gradient_clipping_threshold or 0.0,
        model_average=avg,
    )
    extra = dict(getattr(method, "extra", {}))
    cls = {
        "sgd": O.Momentum,
        "momentum": O.Momentum,
        "adam": O.Adam,
        "adamax": O.AdaMax,
        "adagrad": O.AdaGrad,
        "decayed_adagrad": O.DecayedAdaGrad,
        "adadelta": O.AdaDelta,
        "rmsprop": O.RMSProp,
    }[kind]
    if cls is O.Momentum and "momentum" not in extra and kind == "sgd":
        extra["momentum"] = 0.0
    if cls is O.Adam:
        extra = {
            "beta1": extra.get("beta1", 0.9),
            "beta2": extra.get("beta2", 0.999),
            "epsilon": extra.get("epsilon", 1e-8),
        }
    return cls(**extra, **common)
