"""paddle.v2.minibatch equivalent (reference: python/paddle/v2/minibatch.py)."""

from __future__ import annotations


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group a sample reader into a minibatch reader."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    from paddle_tpu.reader.pass_cache import copy_cache_tags

    # carry the @provider(cache=CACHE_PASS_IN_MEM) tags through to the
    # trainer (reader/pass_cache.py device-resident replay)
    return copy_cache_tags(reader, batch_reader)
