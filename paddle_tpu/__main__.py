"""``python -m paddle_tpu <command>`` — same face as the ``paddle-tpu``
console script (the reference's ``paddle`` wrapper, submit_local.sh.in)."""

import sys

from paddle_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
