"""Elastic master — fault-tolerant task-queue data dispatch (reference:
go/master/service.go, the Go master the v2 python API reaches through
python/paddle/v2/master/client.py).

The reference partitions recordio chunks into tasks and serves them to
stateless trainers over RPC with etcd-snapshotted todo/pending/done/failed
queues; a timed-out pending task is requeued, and a task failing more than
`failure_max` times is discarded (service.go:80-459).  This implementation
keeps the exact queue semantics but is etcd-free: queue snapshots go to a
JSON file (atomic rename) and leadership is a filesystem lease — the TPU
deployment model has a single coordinator host per pod slice, so file-lease
is the idiomatic replacement for etcd election.

Pieces:
  * ``Service``    — the queue state machine (thread-safe, in-process).
  * ``Server``     — serves a Service over ``multiprocessing.connection``
                     (a real process/network boundary like the Go RPC server).
  * ``Client``     — ``set_dataset / next_record / ...`` parity with
                     python/paddle/v2/master/client.py; works against an
                     in-process Service or a remote Server address.

Hostile-network plane (ISSUE 15): every message between Server and Client
rides the master_wire codec — versioned CRC frames over a restricted typed
payload encoder, bounded by ``rpc_max_message_mb`` on send AND recv — so a
corrupt, oversized or version-skewed frame is a counted, structured
rejection, never an exec of peer bytes or an unbounded allocation.
Replies are seq-correlated (duplicated/reordered deliveries discard as
stale), and when a ``net_*`` chaos point is armed the transport itself
injects faults (robustness/netem.py): the retry/timeout/fencing story
below is drilled against delay, drop, duplication, reordering, corruption
and one-way partitions, not just process death.

Durable state plane (``journal=True`` — the mode master_ha runs): every
queue/registry/fence transition appends one CRC-framed, fsync'd record to
an append-only journal (master_journal.py) BEFORE the RPC that caused it is
acknowledged, and the JSON snapshot becomes the journal's periodic
compaction target.  Recovery (or a hot standby tailing the file) replays
``snapshot + journal`` to the exact pre-crash state — task leases stay
warm, per-task result payloads survive, and a failover mid-pass completes
the pass with ZERO recomputed tasks (the etcd-journaled design of
go/master/etcd_client.go, minus etcd).

Elastic cluster plane (the scale-out completion of the Go master's
fault-tolerance model, arXiv:1605.08695 §4.4):
  * worker registry — ``register_worker``/``heartbeat`` leases, pruned by
    the same clock discipline as task leases; a dead worker's pending task
    leases requeue to survivors immediately (the etcd-lease-expiry path of
    go/master/service.go, minus etcd).
  * pass fence — ``fence_arrive``/``fence_status``: a barrier over the LIVE
    membership, so a worker that died (and was pruned) never wedges the
    pass boundary.
  * result plane — ``task_finished(task_id, epoch, result)`` attaches a
    per-task payload (the epoch guard rejects zombie owners);
    ``pass_results`` hands the full map back so every worker reduces the
    pass deterministically in task-id order (trainer/elastic.py).
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import glob as _glob
import json
import logging
import os
import socket as _socket
import struct as _struct
import threading
import time
from multiprocessing.connection import Client as _ConnClient, Listener
from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu import master_journal as _mj
from paddle_tpu import master_wire as _wire
from paddle_tpu import obs as _obs
from paddle_tpu.analysis.lock_sanitizer import make_lock, make_rlock
from paddle_tpu.io import recordio
from paddle_tpu.robustness import chaos as _chaos
from paddle_tpu.robustness import netem as _netem

_log = logging.getLogger("paddle_tpu.master")

__all__ = [
    "Service", "Server", "Client", "MasterRPCError", "MasterTransportError",
    "MasterTimeoutError",
]


class MasterRPCError(RuntimeError):
    """The master executed the call and reported an application error —
    distinct from transport failures so HA clients do not reconnect-retry
    deterministic errors."""


class MasterTransportError(ConnectionError):
    """The TRANSPORT failed (broken pipe / EOF / refused) and the client's
    short reconnect-retry window was exhausted — the call may or may not
    have executed.  Subclasses ConnectionError so HA wrappers (master_ha.
    HAClient) treat it as 'leader gone, re-discover', never as an
    application error."""


class MasterTimeoutError(MasterTransportError):
    """The per-call DEADLINE elapsed with no reply — the socket may be
    half-open (a master that bounced without an RST, a frozen leader) and
    the call may or may not have executed.  Distinct from the generic
    transport error so callers can observe stuck-vs-dead; still a
    MasterTransportError/ConnectionError subclass so every HA
    reconnect-and-rediscover path treats it as 'leader gone' (the whole
    master surface is idempotent-or-epoch-guarded, so the at-least-once
    retry that follows is absorbed server-side)."""


@dataclasses.dataclass
class Task:
    task_id: int
    chunks: List[recordio.Chunk]
    epoch: int = 0  # failure count (reference service.go Task.Epoch)

    def to_json(self):
        return {
            "task_id": self.task_id,
            "epoch": self.epoch,
            "chunks": [
                {"path": c.path, "offset": c.offset, "n_records": c.n_records}
                for c in self.chunks
            ],
        }

    @staticmethod
    def from_json(d):
        return Task(
            d["task_id"],
            [recordio.Chunk(c["path"], c["offset"], c["n_records"]) for c in d["chunks"]],
            d["epoch"],
        )


class Service:
    """Queue state machine: todo / pending / done / failed (reference
    go/master/service.go:80)."""

    def __init__(
        self,
        snapshot_path: Optional[str] = None,
        chunks_per_task: int = 8,
        timeout_s: float = 60.0,
        failure_max: int = 3,
        auto_rotate: bool = True,
        snapshot_min_interval_s: float = 1.0,
        clock=time.time,
        worker_timeout_s: float = 10.0,
        journal: bool = False,
        journal_fsync: bool = True,
        journal_compact_every: int = 512,
    ):
        """auto_rotate=True mirrors the reference: the moment a pass drains,
        done tasks recycle into todo and other trainers stream straight into
        the next pass (pass-end is a per-client observation, service.go:404).
        auto_rotate=False holds the pass boundary until start_new_pass() —
        the synchronized-pass mode a sync-SGD trainer wants.

        ``journal=True`` turns the snapshot file into a journaled state
        plane: transitions append fsync'd records to master_journal files
        next to ``snapshot_path``, the snapshot is rewritten only at
        compaction (every ``journal_compact_every`` records, at
        set_dataset, and at promotion), and recovery replays snapshot +
        journal — keeping task leases, results, registry and fences warm
        across a master death.  ``journal=False`` keeps the legacy
        debounced-snapshot behavior byte-for-byte."""
        self._lock = make_rlock("master.Service._lock")
        self._clock = clock  # injectable for deterministic lease tests
        self.chunks_per_task = chunks_per_task
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.auto_rotate = auto_rotate
        self.snapshot_path = snapshot_path
        self.snapshot_min_interval_s = snapshot_min_interval_s
        self._last_snapshot = 0.0
        self._flush_timer: Optional[threading.Timer] = None
        self.todo: List[Task] = []
        # id -> (task, lease deadline, owner worker id or None)
        self.pending: Dict[int, Tuple[Task, float, Optional[str]]] = {}
        self.done: List[Task] = []
        self.discarded: List[Task] = []
        self.fail_events = 0
        self.pass_id = 0
        self._save_holder: Optional[Tuple[str, float]] = None
        # -- elastic cluster plane (registry / fences / results) ----------
        self.worker_timeout_s = worker_timeout_s
        self.workers: Dict[str, float] = {}  # worker id -> heartbeat deadline
        # pass_id -> {task_id: payload}; only the trailing passes are
        # retained (a slow or late-joining worker may still need pass P's
        # map while P+1 streams)
        self.results: Dict[int, Dict[int, Any]] = {}
        self._pass_done: Dict[int, int] = {}  # pass -> done count at rotation
        # fence id -> {"arrived": set, "released": None | frozen info dict}
        self.fences: Dict[str, Dict[str, Any]] = {}
        # worker id -> attested target pass (see start_new_pass): the
        # failover-regression heal's unanimous-vote ledger, runtime-only
        self._repass_votes: Dict[str, int] = {}
        self._repass_unanimous_since: Optional[float] = None
        # -- durable journal plane (master_journal.py) ---------------------
        self._journaled = bool(journal)
        self._journal_fsync = bool(journal_fsync)
        self.journal_compact_every = int(journal_compact_every)
        self._journal_writer: Optional[_mj.JournalWriter] = None
        self._journal_gen = 0
        self._seq = 0  # last assigned/applied journal sequence number
        self._records_since_compact = 0
        self.replayed_records = 0  # how many journal records recovery applied
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()
        if self._journaled and self.snapshot_path:
            # own the plane: start a fresh generation so (a) the next
            # failover's replay is bounded by THIS leadership's appends and
            # (b) a deposed predecessor's stragglers land in a file no
            # snapshot references
            self._compact(reclaim_orphan=True)

    # -- dataset ---------------------------------------------------------
    def set_dataset(self, patterns: Sequence[str]) -> int:
        """Partition the recordio files into tasks (reference
        service.go:105 partition()).  Idempotent: only the first caller wins,
        like the reference's SetDataset."""
        with self._lock:
            if self.todo or self.pending or self.done:
                return self.n_tasks()
            chunks: List[recordio.Chunk] = []
            for pat in patterns:
                for path in sorted(_glob.glob(pat)):
                    chunks.extend(recordio.scan_chunks(path))
            tasks = []
            for i in range(0, len(chunks), self.chunks_per_task):
                tasks.append(Task(len(tasks), chunks[i : i + self.chunks_per_task]))
            self.todo = tasks
            if self._journaled:
                self._compact()  # structural change: re-anchor the plane
            else:
                self._snapshot(force=True)
            return len(tasks)

    def n_tasks(self) -> int:
        with self._lock:
            return len(self.todo) + len(self.pending) + len(self.done)

    # -- task lifecycle --------------------------------------------------
    def get_task(self, worker_id: Optional[str] = None):
        """Pop a todo task into pending with a lease deadline (reference
        service.go:362 GetTask).  Returns the task dict, the string "wait"
        when all remaining tasks are leased to other workers (mid-pass
        starvation), or None at a pass boundary.  ``worker_id`` (when the
        caller is a registered elastic worker) records the lease owner so
        a pruned worker's leases requeue without waiting out the per-task
        timeout."""
        with self._lock:
            self._prune_workers()
            self._requeue_expired()
            if worker_id is not None:
                # a polling worker is alive by definition: auto-(re)register
                # even if the prune just expired it (prune targets SILENT
                # workers — hung or dead — which never reach this line)
                self._touch_worker(worker_id)
                # at-least-once lease delivery: if THIS worker already holds
                # a pending lease, re-serve it instead of granting another.
                # The healthy flow never hits this (workers ack before the
                # next get_task); it exists for the reply-lost case — the
                # old leader journaled the lease and died before answering,
                # so the standby's replica holds a warm lease the worker
                # never heard about.  Re-serving (with a fresh deadline)
                # completes the delivery; letting it strand would cost a
                # full task-lease timeout + a recompute.
                held = sorted(
                    tid for tid, ent in self.pending.items()
                    if ent[2] == worker_id
                )
                if held:
                    task = self.pending[held[0]][0]
                    self.pending[task.task_id] = (
                        task, self._clock() + self.timeout_s, worker_id
                    )
                    return {
                        "task": task.to_json(),
                        "epoch": task.epoch,
                        "timeout_s": self.timeout_s,
                        "pass_id": self.pass_id,
                    }
            if not self.todo and not self.pending and self.done:
                if not self.auto_rotate:
                    return None  # hold the barrier until start_new_pass()
                self._rotate_pass()
                return None  # signal pass boundary to the observing client
            if not self.todo:
                return "wait" if self.pending else None
            task = self.todo.pop(0)
            self.pending[task.task_id] = (
                task, self._clock() + self.timeout_s, worker_id
            )
            # the lease grant is journaled so a failover keeps it WARM: the
            # new leader serves the in-flight worker's eventual ack instead
            # of re-serving (= recomputing) the task
            self._journal({
                "t": "lease", "task": task.task_id, "epoch": task.epoch,
                "worker": worker_id,
            })
            self._snapshot()
            return {
                "task": task.to_json(),
                "epoch": task.epoch,
                "timeout_s": self.timeout_s,
                # which pass this task belongs to: an elastic worker that
                # believes it is on an earlier pass detects the skew here
                # and catches up BEFORE computing with stale parameters
                "pass_id": self.pass_id,
            }

    def _rotate_pass(self) -> None:
        """Recycle done → todo; epochs reset so past failures don't carry."""
        from_pass = self.pass_id
        self._rotate_pass_state()
        self._journal({"t": "rotate", "from": from_pass})
        self._snapshot(force=True)

    def _advance_pass(self, recycled: List[Task],
                      pass_done_mark: int) -> None:
        """Shared tail of every pass rotation (normal or forced): freeze
        the closing pass's done-count marker (late joiners use it to
        verify a retained result map is COMPLETE before replay-applying
        it; -1 = poisoned, never replayable), recycle ``recycled`` into
        todo at epoch 0, advance the pass, clear the per-pass
        attestations, and trim retention to the trailing passes (a slow
        worker may still be fetching pass P's results while P+1
        streams)."""
        self._pass_done[self.pass_id] = pass_done_mark
        for t in recycled:
            t.epoch = 0
        self.todo = recycled
        self.done = []
        self.pass_id += 1
        self._repass_votes.clear()
        self._repass_unanimous_since = None
        for p in [p for p in self.results if p < self.pass_id - 2]:
            del self.results[p]
        for p in [p for p in self._pass_done if p < self.pass_id - 2]:
            del self._pass_done[p]

    def _rotate_pass_state(self) -> None:
        """The pure state transition of a pass rotation — shared by the
        live path and journal replay (``apply_record``)."""
        self._advance_pass(self.done, len(self.done))

    def _force_rotate_state(self) -> None:
        """The failover-regression transition (see ``start_new_pass``):
        recycle EVERY task — todo, pending, done — into the next pass,
        drop the pass's (unfinishable) result map, and poison its frozen
        done-count so retained-map replay is impossible.  Shared by the
        live path and journal replay (``_apply_frotate``)."""
        p = self.pass_id
        tasks = sorted(
            list(self.todo)
            + [ent[0] for ent in self.pending.values()]
            + list(self.done),
            key=lambda t: t.task_id,
        )
        self.pending = {}
        self.results.pop(p, None)
        self._advance_pass(tasks, -1)

    def start_new_pass(self, target_pass: Optional[int] = None,
                       worker_id: Optional[str] = None) -> int:
        """Explicit pass barrier release (auto_rotate=False mode).

        ``target_pass`` makes the release idempotent for a fleet: the pass
        rotates only while ``pass_id < target_pass``, so a straggler that
        calls ``start_new_pass(p+1)`` after a fast worker already drained
        pass p+1 cannot double-rotate the queue past it.

        ``worker_id`` (failover-regression heal): a registered worker
        calling with ``target_pass > pass_id`` while the queue is NOT
        drained is ATTESTING that it already applied this pass — its
        reduction happened against a deposed leader whose final
        acks/rotation died in that leader's fenced journal generation.
        One vote proves nothing; when EVERY live worker has attested, no
        process exists that could legitimately recompute the re-opened
        tasks (everyone's params already include the pass — recomputed
        contributions would carry post-apply bits), so the master
        FORCE-rotates: the stale queue recycles into the next pass and
        the unfinishable pass's retained result map is POISONED
        (``_pass_done = -1``) so a late joiner can never replay it as
        complete — the committed-manifest fallback is its heal."""
        with self._lock:
            if (
                not self.todo and not self.pending and self.done
                and (target_pass is None or self.pass_id < target_pass)
            ):
                self._rotate_pass()
            elif (
                target_pass is not None and worker_id is not None
                and target_pass > self.pass_id
                and (self.todo or self.pending)
            ):
                self._prune_workers()
                self._repass_votes[worker_id] = target_pass
                live = set(self.workers)
                attested = {
                    w for w, t in self._repass_votes.items()
                    if t > self.pass_id
                }
                if not (live and live <= attested):
                    self._repass_unanimous_since = None
                else:
                    # unanimity must STAY unanimous for a full worker-
                    # timeout window before it can force anything: a
                    # briefly-silent-but-alive worker (GC pause, load
                    # stall) that was just pruned re-registers well
                    # inside that window, re-enters the live set, and —
                    # not attesting — breaks unanimity.  Only a worker
                    # silent long enough to be declared dead everywhere
                    # else in the system can be absent from the vote.
                    now = self._clock()
                    if self._repass_unanimous_since is None:
                        self._repass_unanimous_since = now
                    if now - self._repass_unanimous_since >= (
                        self.worker_timeout_s
                    ):
                        _log.warning(
                            "master: every live worker (%s) attests pass "
                            "%d was already applied on a deposed leader "
                            "(stable for %.1fs) — force-rotating past "
                            "the unrecoverable queue state",
                            sorted(live), self.pass_id,
                            now - self._repass_unanimous_since,
                        )
                        from_pass = self.pass_id
                        self._force_rotate_state()
                        self._journal({"t": "frotate", "from": from_pass})
                        self._snapshot(force=True)
            return self.pass_id

    def renew_lease(self, task_id: int, epoch: int) -> bool:
        """Extend a pending task's lease (consume-then-ack keeps the lease
        open while the trainer drains records; renewal prevents a slow
        consumer's task from expiring into the failure path).  The epoch
        guard rejects a stale holder whose task was already re-served."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            self.pending[task_id] = (
                ent[0], self._clock() + self.timeout_s, ent[2]
            )
            return True

    def task_finished(
        self, task_id: int, epoch: Optional[int] = None, result: Any = None,
        pass_id: Optional[int] = None,
    ) -> bool:
        """epoch (when given) guards against a stale holder acking a task
        that expired and was re-served at a higher epoch — same discipline
        as task_failed (reference service.go:404 checks task epoch).

        ``result`` (elastic workers): the task's reduction payload — e.g. a
        gradient-contribution tree — stored under the current pass for
        ``pass_results``.  A rejected (zombie) ack never stores its result,
        so the surviving re-computation's bits win.

        ``pass_id`` (elastic workers, from the lease's ``get_task`` reply)
        closes the guard rotation re-opens: epochs reset to 0 at every
        rotation, so (task, epoch) alone cannot tell a pass-N ack from a
        pass-N+1 task — a sufficiently delayed retry could land a stale
        contribution in the wrong pass.  A pass-tagged ack for any pass
        but the current one is rejected outright.

        IDEMPOTENT under client retries: a worker whose first ack landed
        but whose reply was lost (master bounce mid-call, per-call deadline
        fired) re-sends the same ``(task, epoch)`` — the duplicate is
        accepted-and-deduped against ``done``, never double-counted.  And
        a pass-tagged ack whose lease record died with a legacy
        (journal-less) master is accepted straight from ``todo`` at the
        matching epoch, so even a cold failover loses no landed
        computation (pass-LESS acks — the legacy streaming client — never
        claim from todo: their task simply re-serves, the flow's normal
        at-least-once story)."""
        if _chaos.fire("kill_master"):
            # the leader-death drill: die BEFORE executing the transition,
            # mid-pass — the worker's retry must land on the standby
            _chaos.kill_self()
        with self._lock:
            if pass_id is not None and pass_id != self.pass_id:
                return False  # cross-pass zombie: that pass already closed
            ent = self.pending.get(task_id)
            if ent is not None and (epoch is None or ent[0].epoch == epoch):
                del self.pending[task_id]
                self.done.append(ent[0])
                self._record_finish(task_id, ent[0].epoch, result)
                return True
            if epoch is None:
                return False
            # duplicate re-ack after a client retry: already done at this
            # epoch — accept and dedupe (store the result only if the first
            # delivery didn't; contributions are deterministic, so either
            # copy carries the same bits)
            for t in self.done:
                if t.task_id == task_id and t.epoch == epoch:
                    cur = self.results.get(self.pass_id, {})
                    if result is not None and task_id not in cur:
                        self._record_finish(task_id, epoch, result)
                    return True
            # post-failover ack: the lease evaporated with the old master
            # (legacy snapshot recovery requeues pending) but the worker's
            # computation is done — accept it from todo at the matching
            # epoch instead of forcing a recompute.  Pass-tagged acks only:
            # rotation resets epochs, so an untagged ack could claim a
            # LATER pass's copy of the task (the guard above already
            # rejected tagged acks for a closed pass)
            if pass_id is None:
                return False
            for i, t in enumerate(self.todo):
                if t.task_id == task_id and t.epoch == epoch:
                    self.todo.pop(i)
                    self.done.append(t)
                    self._record_finish(task_id, epoch, result)
                    return True
            return False

    def _record_finish(self, task_id: int, epoch: int, result) -> None:
        """One acked completion: retain the result payload for the current
        pass, journal the transition, publish.  Caller holds the lock and
        has already moved the task into ``done``."""
        if result is not None:
            self.results.setdefault(self.pass_id, {})[task_id] = result
        self._journal({
            "t": "finish", "task": task_id, "epoch": epoch,
            "pass": self.pass_id, "result": result,
        })
        self._snapshot()

    def task_failed(self, task_id: int, epoch: int) -> bool:
        """(reference service.go:442 TaskFailed → processFailedTask:308)"""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            del self.pending[task_id]
            self._process_failed(ent[0])
            self._journal({"t": "fail", "task": task_id, "epoch": epoch})
            self._snapshot()
            return True

    def task_returned(self, task_id: int, epoch: int) -> bool:
        """Graceful give-back: a client closing with unconsumed records hands
        its task back to the todo queue WITHOUT burning a failure event —
        deliberate abandonment (early stop, capped test pass) is not a crash,
        and must not walk the task toward the failure_max discard."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            del self.pending[task_id]
            self.todo.append(ent[0])
            self._journal({"t": "ret", "task": task_id, "epoch": epoch})
            self._snapshot()
            return True

    def _process_failed(self, task: Task) -> None:
        """epoch++, discard past failure_max, else requeue (service.go:308)."""
        self.fail_events += 1
        task.epoch += 1
        if task.epoch >= self.failure_max:
            self.discarded.append(task)  # discard (service.go:336)
        else:
            self.todo.append(task)

    def _requeue_expired(self) -> None:
        now = self._clock()
        expired = [tid for tid, ent in self.pending.items() if ent[1] < now]
        for tid in expired:
            task = self.pending.pop(tid)[0]
            epoch = task.epoch  # _process_failed bumps it; journal the
            self._process_failed(task)  # epoch the replayed pop must match
            self._journal({"t": "fail", "task": tid, "epoch": epoch})

    # -- elastic cluster plane: registry / fences / results ---------------
    def _touch_worker(self, worker_id: str) -> None:
        """(Re)grant the worker's registry lease; callers hold the lock.
        Journal AFTER the insert: _journal may compact, and the snapshot it
        publishes must already contain the transition (the record's seq
        folds below the snapshot's base)."""
        is_new = worker_id not in self.workers
        self.workers[worker_id] = self._clock() + self.worker_timeout_s
        if is_new:
            # a (re)joining incarnation must not inherit a dead one's
            # force-rotate attestation: a restarted worker whose params
            # never applied the attested pass would otherwise keep a
            # spurious unanimity alive and get stranded by its own
            # ghost's vote
            self._repass_votes.pop(worker_id, None)
            self._journal({"t": "join", "worker": worker_id})

    def register_worker(self, worker_id: str) -> Dict[str, Any]:
        """Join (or rejoin) the worker registry under a heartbeat lease.
        Returns the cluster view the worker needs to enter the pass loop —
        idempotent, so a worker that outlived a master failover (the new
        leader recovers queues from the snapshot but the registry is
        runtime state) just re-registers."""
        with self._lock:
            self._prune_workers()
            self._touch_worker(worker_id)
            return {
                "pass_id": self.pass_id,
                "timeout_s": self.worker_timeout_s,
                "auto_rotate": self.auto_rotate,
                "workers": sorted(self.workers),
            }

    def heartbeat(self, worker_id: str) -> bool:
        """Renew the registry lease; False means the worker expired (or the
        master failed over) and must ``register_worker`` again."""
        with self._lock:
            self._prune_workers()
            if worker_id not in self.workers:
                return False
            self.workers[worker_id] = self._clock() + self.worker_timeout_s
            return True

    def deregister_worker(self, worker_id: str) -> None:
        """Graceful leave: held task leases go back to todo WITHOUT a
        failure event (the task_returned discipline — leaving is not a
        crash)."""
        with self._lock:
            self._repass_votes.pop(worker_id, None)
            if self.workers.pop(worker_id, None) is not None:
                self._journal({"t": "leave", "worker": worker_id})
            held = [
                tid for tid, ent in self.pending.items() if ent[2] == worker_id
            ]
            for tid in held:
                task = self.pending.pop(tid)[0]
                self.todo.append(task)
                self._journal({"t": "ret", "task": tid, "epoch": task.epoch})
            if held:
                self._snapshot()

    def live_workers(self) -> List[str]:
        with self._lock:
            self._prune_workers()
            return sorted(self.workers)

    def _prune_workers(self) -> None:
        """Expire silent workers and requeue their task leases NOW — the
        kill-one-of-N path: a dead worker costs one registry lease timeout,
        not the job (and not even the longer per-task lease timeout)."""
        now = self._clock()
        dead = [w for w, dl in self.workers.items() if dl < now]
        for w in dead:
            del self.workers[w]
            self._journal({"t": "leave", "worker": w, "pruned": True})
            held = [tid for tid, ent in self.pending.items() if ent[2] == w]
            for tid in held:
                task = self.pending.pop(tid)[0]
                epoch = task.epoch
                self._process_failed(task)
                self._journal({"t": "fail", "task": tid, "epoch": epoch})
            if held:
                self._snapshot()

    def fence_arrive(
        self, fence_id: str, worker_id: str,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Arrive at a barrier.  The fence releases once every LIVE worker
        has arrived (membership is evaluated per poll, so a worker that
        died — and was pruned — never wedges the boundary).  Release
        freezes the arrived set and the done-task count: late arrivals see
        the frozen view and can tell they missed the membership cut.

        ``meta`` declares per-worker capabilities; ``{"ckpt": True}`` opts
        the worker into the frozen ``writers`` set, so the shard-writer
        roster is negotiated among checkpoint-enabled workers rather than
        assumed equal to the whole membership (one checkpoint-less worker
        must not doom every manifest commit)."""
        with self._lock:
            f = self.fences.setdefault(
                fence_id, {"arrived": set(), "released": None, "meta": {}}
            )
            if f["released"] is None and worker_id not in f["arrived"]:
                # journal FIRST arrivals only: fence polling re-arrives at
                # worker heartbeat cadence and must not flood the journal
                f["arrived"].add(worker_id)
                if meta:
                    f["meta"][worker_id] = dict(meta)
                _obs.instant(
                    "fence_arrive", cat="master",
                    fence=fence_id, worker=worker_id,
                )
                self._journal({
                    "t": "farrive", "fence": fence_id, "worker": worker_id,
                    "meta": dict(meta) if meta else None,
                })
            elif f["released"] is None and meta:
                changed = f["meta"].get(worker_id) != dict(meta)
                f["meta"][worker_id] = dict(meta)
                if changed:
                    # a CHANGED meta on re-arrival is durable state too —
                    # the frozen writers roster derives from it, so a warm
                    # standby must see the update (re-journaling farrive is
                    # replay-idempotent: set-add + meta overwrite).  The
                    # unchanged re-arrivals of fence polling still skip the
                    # journal, keeping the no-flood property
                    self._journal({
                        "t": "farrive", "fence": fence_id,
                        "worker": worker_id, "meta": dict(meta),
                    })
            if worker_id in self.workers:
                # arriving (and re-arriving while polling) is a liveness
                # signal: renew so a worker parked at a slow barrier is
                # never pruned mid-wait.  Renew-only — a PRUNED worker
                # re-joins through register_worker/get_task, keeping the
                # missed-the-membership-cut semantics observable.
                self.workers[worker_id] = self._clock() + self.worker_timeout_s
            if len(self.fences) > 64:  # bound runtime state
                for stale in list(self.fences)[: len(self.fences) - 64]:
                    if stale != fence_id:
                        del self.fences[stale]
            return self._fence_view(fence_id)

    def fence_status(self, fence_id: str) -> Dict[str, Any]:
        with self._lock:
            return self._fence_view(fence_id)

    def _fence_view(self, fence_id: str) -> Dict[str, Any]:
        f = self.fences.get(fence_id)
        if f is None:
            return {"known": False, "released": False}
        if f["released"] is None:
            self._prune_workers()
            members = None
            if self.workers and set(self.workers) <= f["arrived"]:
                members = sorted(f["arrived"] & set(self.workers))
            elif not self.workers and f["arrived"]:
                # no registry (legacy/single-worker use): whoever arrived
                # is the membership
                members = sorted(f["arrived"])
            if members is not None:
                f["released"] = {
                    "workers": members,
                    "writers": [
                        w for w in members
                        if f["meta"].get(w, {}).get("ckpt")
                    ],
                    "n_done": len(self.done),
                    "pass_id": self.pass_id,
                }
                _obs.instant(
                    "fence_release", cat="master", fence=fence_id,
                    workers=members,
                )
                # the frozen membership view is durable state: a standby
                # taking over mid-barrier must release the SAME view, not
                # re-evaluate membership it never observed
                self._journal({
                    "t": "frelease", "fence": fence_id,
                    "view": dict(f["released"]),
                })
        if f["released"] is None:
            return {
                "known": True, "released": False,
                "n_arrived": len(f["arrived"]),
            }
        return {"known": True, "released": True, **f["released"]}

    def pass_results(self, pass_id: int) -> Dict[str, Any]:
        """``{"results": {task_id: payload}, "n_done": int|None}`` for one
        pass — every worker reduces the map in sorted task-id order, so the
        update is bit-identical fleet-wide regardless of which worker
        computed which task.  ``n_done`` is the pass's frozen done count
        once it rotated (None while the pass is still current — the fence
        view carries the authoritative count there): a late joiner replays
        a retained pass only when ``len(results) == n_done``."""
        with self._lock:
            return {
                "results": dict(self.results.get(pass_id, {})),
                "n_done": self._pass_done.get(pass_id),
            }

    def requeue_unresulted(self) -> int:
        """Move done tasks that have NO stored result for the current pass
        back to todo.  After a master failover the queue snapshot survives
        but the in-memory result payloads do not; recomputing the orphaned
        tasks is safe because contributions are deterministic per task.
        Returns the number requeued.  (Never call this from the legacy
        record-streaming flow — its done tasks legitimately carry no
        results.)"""
        with self._lock:
            have = self.results.get(self.pass_id, {})
            orphaned = [t for t in self.done if t.task_id not in have]
            if orphaned:
                self.done = [t for t in self.done if t.task_id in have]
                self.todo.extend(orphaned)
                self._journal({
                    "t": "unres", "tasks": [t.task_id for t in orphaned],
                })
                self._snapshot()
            return len(orphaned)

    def stats(self) -> Dict[str, Any]:
        """Cluster-plane observability snapshot (cheap, lock-consistent)."""
        with self._lock:
            self._prune_workers()
            return {
                "pass_id": self.pass_id,
                "n_todo": len(self.todo),
                "n_pending": len(self.pending),
                "n_done": len(self.done),
                "n_discarded": len(self.discarded),
                "fail_events": self.fail_events,
                "workers": sorted(self.workers),
                # codec-rejection observability: the corrupt-frame drills
                # assert server_rejected_frames > 0 IN-RUN through this
                # field (Server and Service share the process, so the
                # module counters are one coherent view)
                "wire": _wire.counters.snapshot(),
            }

    # -- save-model arbitration (reference service.go:461-497) -----------
    def request_save_model(self, trainer_id: str, block_secs: float) -> bool:
        """Exactly one trainer in each window gets True."""
        with self._lock:
            now = self._clock()
            if self._save_holder and self._save_holder[1] > now:
                return self._save_holder[0] == trainer_id
            self._save_holder = (trainer_id, now + block_secs)
            return True

    # -- snapshot / journal / recover (service.go:165-273, etcd → file) --
    def fence(self) -> None:
        """Stop this (deposed) Service from ever writing the shared snapshot
        OR appending to the shared journal again, and cancel any pending
        debounced flush — a new leader owns the files now (the etcd design
        gets this for free from leases on keys)."""
        with self._lock:
            self.snapshot_path = None
            if self._journal_writer is not None:
                self._journal_writer.close()
                self._journal_writer = None
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None

    def _snapshot(self, force: bool = False) -> None:
        """Legacy (journal-less) persistence — debounced: per-task
        transitions at most one write per snapshot_min_interval_s; a skipped
        write is flushed by a timer so the last transition of a burst always
        reaches disk.  Structural changes (set_dataset, pass rotation)
        always write.  In journaled mode this is a no-op: the fsync'd
        journal append IS the per-transition durability point, and the
        snapshot is rewritten only at compaction."""
        if not self.snapshot_path or self._journaled:
            return
        now = time.time()
        if not force and now - self._last_snapshot < self.snapshot_min_interval_s:
            if self._flush_timer is None:
                t = threading.Timer(self.snapshot_min_interval_s, self._flush)
                t.daemon = True
                self._flush_timer = t
                t.start()
            return
        self._last_snapshot = now
        self._write_snapshot()

    def _flush(self) -> None:
        with self._lock:
            self._flush_timer = None
            if not self.snapshot_path:
                return  # fenced between schedule and fire
            self._last_snapshot = time.time()
            self._write_snapshot()

    def _journal(self, rec: Dict[str, Any]) -> None:
        """Append one fsync'd record; compact when the generation has grown
        past ``journal_compact_every`` records.  No-op unless journaled
        (and not fenced).  Caller holds the lock."""
        if not self._journaled or self._journal_writer is None:
            return
        self._seq += 1
        self._journal_writer.append(self._seq, rec)
        self._records_since_compact += 1
        if self._records_since_compact >= self.journal_compact_every:
            self._compact()

    def _compact(self, reclaim_orphan: bool = False) -> None:
        """Fold the journal into the snapshot and start a new generation.

        Crash-ordering: (1) write + fsync the NEW journal generation with
        the retained per-pass results re-emitted into it (seq > the
        snapshot's base, so replay re-applies them — the snapshot itself
        stays pure JSON and never carries binary payloads); (2) atomically
        publish the snapshot referencing the new generation; (3) delete
        older generations.  A crash before (2) leaves the old snapshot +
        old journal fully consistent (the new file is an unreferenced
        orphan); a crash before (3) leaves a stale-but-unreferenced old
        generation that the next compaction sweeps.

        Fencing: compaction REWRITES the shared plane (truncates into a
        generation file, replaces the snapshot, sweeps the rest), so a
        deposed-but-not-yet-fenced leader running it would corrupt the new
        leader's live state — the append-side fence ("stragglers land in
        an unreferenced file") does not cover it.  Two guards: the
        published snapshot is the ownership record (referencing a
        generation we did not write means someone else owns the plane →
        fence, return), and the new generation is created EXCLUSIVELY (a
        mid-life collision means a racing new leader → fence).  Only a
        caller that just acquired the HA lease (boot recovery, promote)
        may pass ``reclaim_orphan=True`` to take over a predecessor's
        crash orphan — a compaction that died before publishing."""
        if not self._journaled or not self.snapshot_path:
            return
        d = os.path.dirname(self.snapshot_path) or "."
        # ownership precheck parses the snapshot every time: compaction is
        # already O(dataset) (result re-emission + full snapshot rewrite),
        # and a stat-compare shortcut could miss a new leader's publish
        # (coarse mtime + equal size + recycled inode) — fencing must not
        # ride on that
        try:
            with open(self.snapshot_path) as f:
                published = json.load(f).get("journal_file")
        except (OSError, ValueError):
            published = None  # fresh cluster: no snapshot yet
        if published is not None and published != _mj.journal_filename(
            self._journal_gen
        ):
            if not reclaim_orphan:
                self.fence()  # deposed: a new leader published its gen
                return
            # we hold the FRESH lease (boot/promote): an unexpected
            # publisher is a deposed zombie's last-gasp compaction in the
            # lease-gap window — the RIGHTFUL leader must not cede the
            # plane (fencing here would leave it serving with snapshot and
            # journal silently OFF).  Adopt the published generation as
            # the base and re-anchor above it; the zombie's stragglers are
            # swept with its file
            _log.warning(
                "compaction: snapshot references %s, not our generation "
                "%s — reclaiming the plane over a deposed leader's "
                "last-gasp publish (we hold the fresh lease)",
                published, _mj.journal_filename(self._journal_gen),
            )
            self._journal_gen = _mj.parse_generation(published)
        base_seq = self._seq
        gen_at_entry = self._journal_gen
        self._journal_gen += 1
        fname = _mj.journal_filename(self._journal_gen)
        jpath = os.path.join(d, fname)
        writer = None
        try:
            try:
                writer = _mj.JournalWriter(
                    jpath, fsync=self._journal_fsync, exclusive=True
                )
            except FileExistsError:
                if not reclaim_orphan:
                    self._journal_gen = gen_at_entry  # honest while fenced
                    self.fence()  # a racing new leader created it: deposed
                    return
                # a predecessor's unpublished file sits on our target name:
                # a crash orphan — or a zombie's compaction STILL IN FLIGHT.
                # Removing and recreating the name would defeat the O_EXCL
                # fence the zombie's own publish path relies on, so NEVER
                # reuse a contested name: skip above it (the post-publish
                # sweep collects the leftovers)
                while writer is None:
                    self._journal_gen += 1
                    fname = _mj.journal_filename(self._journal_gen)
                    jpath = os.path.join(d, fname)
                    try:
                        writer = _mj.JournalWriter(
                            jpath, fsync=self._journal_fsync, exclusive=True
                        )
                    except FileExistsError:
                        continue
            for p in sorted(self.results):
                for tid in sorted(self.results[p]):
                    self._seq += 1
                    writer.append(self._seq, {
                        "t": "finish", "task": tid, "pass": p,
                        "result": self.results[p][tid],
                    }, sync=False)
            writer.sync()  # one fsync covers the whole re-emission
            # last-moment ownership re-verify: if we stalled past the lease
            # DURING this compaction (e.g. a slow fsync), a new leader may
            # have re-anchored the plane — and since reclaim skips
            # contested names, our O_EXCL create cannot catch that case.
            # Publishing now would replace the rightful leader's snapshot
            # with stale state, so the snapshot must still reference what
            # it referenced when we prechecked ownership.
            try:
                with open(self.snapshot_path) as f:
                    published_now = json.load(f).get("journal_file")
            except (OSError, ValueError):
                published_now = None
            if published_now != published:
                writer.close()
                try:
                    os.remove(jpath)
                except OSError:
                    pass
                self.fence()  # deposed mid-compaction
                return
            self._write_snapshot(seq=base_seq, journal_file=fname)
        except OSError as exc:
            # transient disk failure (ENOSPC, EIO) mid-compaction.  Roll
            # the generation back so the ownership precheck keeps matching
            # the published snapshot — a dangling bump would make the NEXT
            # attempt self-fence this healthy leader, after which every
            # acked transition would silently skip the journal.  With a
            # live old writer we keep appending durably to the old
            # generation and retry after another journal_compact_every
            # records; at boot/promote there is no old writer to fall back
            # to (durability would be OFF), so the failure must propagate.
            if writer is not None:
                writer.close()
                try:
                    os.remove(jpath)  # else the retry would hit O_EXCL
                except OSError:
                    pass
            self._journal_gen = gen_at_entry
            self._records_since_compact = 0
            if self._journal_writer is None:
                raise
            _log.warning(
                "journal compaction into %s failed (%s: %s) — keeping the "
                "current generation, will retry", fname,
                type(exc).__name__, exc,
            )
            return
        old_writer, self._journal_writer = self._journal_writer, writer
        if old_writer is not None:
            old_writer.close()
        # Sweep ONLY generations strictly below our own.  An "everything
        # but fname" sweep re-opens the fencing hole the publish path just
        # closed: a zombie stalled between its publish and its sweep can
        # wake to find a new leader re-anchored ABOVE it (reclaim adopts
        # the published generation as its base), and deleting higher-
        # numbered files would unlink the live generation the current
        # snapshot references — every transition acked after that would be
        # invisible to recovery.  Generations are monotonic, so "< ours"
        # only ever collects our own predecessors and crash orphans we
        # skipped; a zombie's higher-numbered orphan survives until a
        # later sweep passes above it.
        for stale in _glob.glob(os.path.join(d, "master_journal-*.log")):
            if _mj.parse_generation(stale) < self._journal_gen:
                try:
                    os.remove(stale)
                except OSError:
                    pass
        self._records_since_compact = 0

    def state_dict(self) -> Dict[str, Any]:
        """The JSON-able snapshot of everything but result payloads (those
        live in the journal).  Caller holds the lock."""
        return {
            "pass_id": self.pass_id,
            "todo": [t.to_json() for t in self.todo],
            "pending": [
                {"task": t.to_json(), "deadline": dl, "owner": owner}
                for (t, dl, owner) in self.pending.values()
            ],
            "done": [t.to_json() for t in self.done],
            "discarded": [t.to_json() for t in self.discarded],
            "fail_events": self.fail_events,
            "workers": sorted(self.workers),
            "pass_done": {str(p): n for p, n in self._pass_done.items()},
            "fences": {
                fid: {
                    "arrived": sorted(f["arrived"]),
                    "meta": f["meta"],
                    "released": f["released"],
                }
                for fid, f in self.fences.items()
            },
        }

    def _write_snapshot(
        self, seq: Optional[int] = None, journal_file: Optional[str] = None
    ) -> None:
        state = self.state_dict()
        if self._journaled:
            state["version"] = 2
            state["seq"] = self._seq if seq is None else seq
            state["journal_file"] = journal_file
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            if self._journaled:
                # compaction publish: the snapshot must be durable before
                # the old generation is swept.  Legacy mode stays the
                # best-effort debounced write it always was.
                f.flush()
                os.fsync(f.fileno())  # lock: allow[C304] compaction publish: the snapshot must be durable before the old journal generation is swept — fsync-before-ack IS the durability contract
        os.replace(tmp, self.snapshot_path)

    def load_state(self, state: Dict[str, Any], warm: bool = True) -> None:
        """Restore from a v2 (journaled) snapshot dict.  ``warm=True``
        keeps OWNED pending leases pending with FRESH deadlines (the
        failover path: the owners are probably alive and mid-compute, and
        their retried acks / re-served get_tasks key on the owner id); the
        owners that aren't expire into the normal failure path.  An
        OWNERLESS lease (a legacy streaming client's) requeues immediately
        even when warm: its holder has no identity to re-serve to, so
        keeping it warm would just stall the pass for a full task timeout —
        and the holder's eventual ack still lands via the matching-epoch
        accept-from-todo branch of ``task_finished``."""
        with self._lock:
            now = self._clock()
            self.pass_id = state["pass_id"]
            self.todo = [Task.from_json(t) for t in state["todo"]]
            self.done = [Task.from_json(t) for t in state["done"]]
            self.discarded = [
                Task.from_json(t) for t in state.get("discarded", [])
            ]
            self.pending = {}
            for ent in state["pending"]:
                task = Task.from_json(ent["task"])
                owner = ent.get("owner")
                if warm and owner is not None:
                    self.pending[task.task_id] = (
                        task, now + self.timeout_s, owner
                    )
                else:
                    self.todo.append(task)
            self.fail_events = int(state.get("fail_events", 0))
            self.workers = {
                w: now + self.worker_timeout_s
                for w in state.get("workers", [])
            }
            self._pass_done = {
                int(p): n for p, n in state.get("pass_done", {}).items()
            }
            self.fences = {
                fid: {
                    "arrived": set(f.get("arrived", ())),
                    "meta": dict(f.get("meta", {})),
                    "released": f.get("released"),
                }
                for fid, f in state.get("fences", {}).items()
            }
            self.results = {}
            self._seq = int(state.get("seq", 0))

    def apply_record(self, seq: int, rec: Dict[str, Any]) -> bool:
        """Replay one journal record onto this state (recovery, and the hot
        standby's tail loop).  Sequence-guarded: a double-delivered record
        (re-read tail, compaction re-emission already applied) is a no-op,
        so replay is idempotent.  Unknown record types are a HARD error —
        a version-skewed or corrupt record must never silently vanish from
        a recovery."""
        with self._lock:
            if seq <= self._seq:
                return False
            t = rec.get("t")
            if t not in _mj.RECORD_TYPES:
                raise _mj.JournalError(
                    f"unknown journal record type {t!r} at seq {seq} — "
                    f"refusing to recover past it (version skew or "
                    f"corruption; run `paddle-tpu lint --journal`)"
                )
            getattr(self, f"_apply_{t}")(rec)
            self._seq = seq
            self.replayed_records += 1
            return True

    # -- per-record replay ops (pure state; never journal, never prune) --
    def _pop_todo(self, task_id: int, epoch: Optional[int]) -> Optional[Task]:
        for i, task in enumerate(self.todo):
            if task.task_id == task_id and (
                epoch is None or task.epoch == epoch
            ):
                return self.todo.pop(i)
        return None

    def _apply_lease(self, rec) -> None:
        task = self._pop_todo(rec["task"], rec.get("epoch"))
        if task is not None:
            self.pending[task.task_id] = (
                task, self._clock() + self.timeout_s, rec.get("worker")
            )

    def _apply_finish(self, rec) -> None:
        p, tid, epoch = rec["pass"], rec["task"], rec.get("epoch")
        if rec.get("result") is not None:
            self.results.setdefault(p, {})[tid] = rec["result"]
        if p != self.pass_id:
            return  # compaction re-emission for a retained earlier pass
        ent = self.pending.get(tid)
        if ent is not None and (epoch is None or ent[0].epoch == epoch):
            del self.pending[tid]
            self.done.append(ent[0])
            return
        task = self._pop_todo(tid, epoch)
        if task is not None:
            self.done.append(task)
        # else: already done (double delivery across generations) — dedupe

    def _apply_fail(self, rec) -> None:
        tid, epoch = rec["task"], rec["epoch"]
        ent = self.pending.get(tid)
        if ent is not None and ent[0].epoch == epoch:
            del self.pending[tid]
            self._process_failed(ent[0])
            return
        task = self._pop_todo(tid, epoch)
        if task is not None:
            self._process_failed(task)

    def _apply_ret(self, rec) -> None:
        ent = self.pending.get(rec["task"])
        if ent is not None and ent[0].epoch == rec["epoch"]:
            del self.pending[rec["task"]]
            self.todo.append(ent[0])

    def _apply_rotate(self, rec) -> None:
        if self.pass_id != rec["from"]:
            _log.warning(
                "journal replay: rotate record for pass %d but replica is "
                "at pass %d — skipping (divergence heals via "
                "requeue_unresulted)", rec["from"], self.pass_id,
            )
            return
        self._rotate_pass_state()

    def _apply_frotate(self, rec) -> None:
        if self.pass_id != rec["from"]:
            _log.warning(
                "journal replay: force-rotate record for pass %d but "
                "replica is at pass %d — skipping", rec["from"],
                self.pass_id,
            )
            return
        self._force_rotate_state()

    def _apply_unres(self, rec) -> None:
        ids = set(rec["tasks"])
        moved = [t for t in self.done if t.task_id in ids]
        self.done = [t for t in self.done if t.task_id not in ids]
        self.todo.extend(moved)
        for t in moved:
            self.results.get(self.pass_id, {}).pop(t.task_id, None)

    def _apply_join(self, rec) -> None:
        self.workers[rec["worker"]] = self._clock() + self.worker_timeout_s

    def _apply_leave(self, rec) -> None:
        self.workers.pop(rec["worker"], None)

    def _apply_farrive(self, rec) -> None:
        f = self.fences.setdefault(
            rec["fence"], {"arrived": set(), "released": None, "meta": {}}
        )
        if f["released"] is None:
            f["arrived"].add(rec["worker"])
            if rec.get("meta"):
                f["meta"][rec["worker"]] = dict(rec["meta"])
        if len(self.fences) > 64:  # mirror the live bound
            for stale in list(self.fences)[: len(self.fences) - 64]:
                if stale != rec["fence"]:
                    del self.fences[stale]

    def _apply_frelease(self, rec) -> None:
        f = self.fences.setdefault(
            rec["fence"], {"arrived": set(), "released": None, "meta": {}}
        )
        f["released"] = dict(rec["view"])
        f["arrived"].update(rec["view"].get("workers", ()))

    def promote(
        self,
        snapshot_path: str,
        journal_fsync: Optional[bool] = None,
        journal_compact_every: Optional[int] = None,
    ) -> None:
        """Turn a replayed standby replica into THE serving, journaling
        leader: refresh every lease deadline (standby deadlines are stale
        by construction — the owners get a full fresh window before the
        prune/expiry discipline judges them), then compact into a fresh
        journal generation this instance owns."""
        with self._lock:
            now = self._clock()
            self.snapshot_path = snapshot_path
            self._journaled = True
            if journal_fsync is not None:
                self._journal_fsync = bool(journal_fsync)
            if journal_compact_every is not None:
                self.journal_compact_every = int(journal_compact_every)
            pending, self.pending = self.pending, {}
            for tid, (task, _dl, owner) in pending.items():
                if owner is not None:
                    self.pending[tid] = (task, now + self.timeout_s, owner)
                else:
                    # replayed ownerless lease (legacy streaming client):
                    # same requeue-now rationale as load_state — no
                    # identity to re-serve to, the epoch-matched ack from
                    # todo still lands
                    self.todo.append(task)
            for w in list(self.workers):
                self.workers[w] = now + self.worker_timeout_s
            self._compact(reclaim_orphan=True)  # we hold the fresh lease

    def _recover(self) -> None:
        with open(self.snapshot_path) as f:
            state = json.load(f)
        if state.get("journal_file") is not None:
            # journaled-shape recovery: warm state + bounded journal replay
            self.load_state(state, warm=True)
            d = os.path.dirname(self.snapshot_path) or "."
            self._journal_gen = _mj.parse_generation(state["journal_file"])
            jpath = os.path.join(d, state["journal_file"])
            if os.path.exists(jpath):
                records, info = _mj.read_records(jpath)
                if info["corrupt"]:
                    # the prefix is consistent; anything past the rot is
                    # healed by lease expiry + requeue_unresulted recompute
                    _log.warning(
                        "journal %s: %s — recovered the good prefix "
                        "(%d records)", jpath, info["error"], len(records),
                    )
                for seq, rec in records:
                    self.apply_record(seq, rec)
            return
        # legacy snapshot (journal-less master, or an upgrade boot)
        self.pass_id = state["pass_id"]
        self.todo = [Task.from_json(t) for t in state["todo"]]
        self.done = [Task.from_json(t) for t in state["done"]]
        self.discarded = [Task.from_json(t) for t in state.get("discarded", [])]
        # pending leases do not survive a legacy master restart: requeue
        # immediately (the reference instead waits for timeout; restart is
        # the slow path).  A landed-but-unleased computation still counts:
        # task_finished accepts a matching-epoch ack straight from todo.
        for ent in state["pending"]:
            self.todo.append(Task.from_json(ent["task"]))


def reader_over(next_record_fn):
    """Reader-creator over a next_record callable: one call = one pass
    (shared by Client and master_ha.HAClient)."""

    def _reader():
        while True:
            rec = next_record_fn()
            if rec is None:
                return
            yield rec

    return _reader


# ---------------------------------------------------------------------------
# RPC layer
# ---------------------------------------------------------------------------

def _dial_with_deadline(address, authkey: bytes, timeout: Optional[float]):
    """Connect + authenticate with a hard deadline.

    The TCP connect itself fails fast against a dead port (RST), but the
    multiprocessing auth handshake can block FOREVER against a half-open
    peer — a listener that accepted into its backlog and then froze (the
    exact state a bouncing master leaves behind).  The stock _ConnClient
    has no timeout hook, so the dial runs in a watchdog'd helper thread:
    on deadline the caller raises :class:`MasterTimeoutError` and the
    helper, when (if) it finally returns, closes the abandoned connection
    itself.  A timed-out dial parks one daemon thread on the dead socket —
    bounded by the caller's retry budget, and freed when the peer's TCP
    stack gives up."""
    if timeout is None:
        return _ConnClient(tuple(address), authkey=authkey)
    box: Dict[str, Any] = {}
    done = threading.Event()
    abandoned = threading.Event()
    lock = make_lock("master._dial_handoff")  # serializes the store-vs-abandon handoff

    def _dial():
        try:
            conn = _ConnClient(tuple(address), authkey=authkey)
            with lock:
                if abandoned.is_set():
                    conn.close()
                else:
                    box["conn"] = conn
        except Exception as exc:  # noqa: BLE001 — re-raised by the caller
            box["err"] = exc
        finally:
            done.set()

    t = threading.Thread(target=_dial, name="paddle-master-dial",
                         daemon=True)
    t.start()
    if not done.wait(timeout):
        # the helper may complete the dial concurrently with this timeout:
        # under the lock, exactly one side owns (and closes) the conn
        with lock:
            abandoned.set()
            conn = box.pop("conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        raise MasterTimeoutError(
            f"master dial {tuple(address)}: no auth handshake in {timeout}s "
            f"(half-open listener?)"
        )
    if "err" in box:
        raise box["err"]
    _set_io_timeouts(box["conn"], timeout)
    return box["conn"]


def _set_io_timeouts(conn, timeout: float) -> None:
    """Arm SO_RCVTIMEO + SO_SNDTIMEO on the connection's socket.
    ``poll(deadline)`` bounds the wait for the FIRST byte of a reply, but
    Connection.recv() then blocks until the complete message arrives, and
    Connection.send() blocks whenever the peer stops draining its socket
    (a multi-MB pickled gradient tree vs a SIGSTOP'd leader fills the
    kernel buffer) — either way a frozen peer would hang the client past
    every deadline.  With i/o timeouts on the shared file description, a
    stalled read/write raises BlockingIOError, which ``_call`` translates
    into :class:`MasterTimeoutError`.  Best-effort: where the socket op
    is unavailable the poll() deadline still covers the no-reply case."""
    if os.name != "posix":
        # the raw struct-timeval pack below is POSIX layout; Windows
        # reads SO_RCVTIMEO as a DWORD of MILLISECONDS and would misread
        # tv_sec as ms, arming absurdly short timeouts — skip, keeping
        # the poll() deadline coverage
        return
    try:
        s = _socket.socket(fileno=os.dup(conn.fileno()))
    except OSError:
        return
    try:
        tv_sec = int(timeout)
        tv_usec = int((timeout - tv_sec) * 1_000_000)
        tv = _struct.pack("ll", tv_sec, tv_usec)
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVTIMEO, tv)
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDTIMEO, tv)
    except OSError:
        pass
    finally:
        s.close()


_METHODS = ("set_dataset", "get_task", "task_finished", "task_failed",
            "task_returned", "renew_lease", "request_save_model", "n_tasks",
            "start_new_pass",
            # elastic cluster plane
            "register_worker", "heartbeat", "deregister_worker",
            "live_workers", "fence_arrive", "fence_status", "pass_results",
            "requeue_unresulted", "stats")


class Server:
    """Serve a Service over multiprocessing.connection — the process/network
    boundary of the Go master's net/rpc server.  Every message rides the
    master_wire codec (versioned CRC framing over the restricted typed
    payload encoder): a corrupt, oversized or unknown-version frame is
    REJECTED — counted, answered with a structured wire-reject the client
    retries through — and never crashes the accept loop, never allocates
    unbounded, never deserializes damaged bytes.  ``max_message_bytes``
    bounds both directions (default: the ``rpc_max_message_mb`` flag)."""

    def __init__(self, service: Service, address=("127.0.0.1", 0), authkey=b"paddle-tpu",
                 sleep=time.sleep, max_message_bytes: Optional[int] = None,
                 methods: Optional[Tuple[str, ...]] = None,
                 backlog: int = 16):
        """``methods``: the RPC whitelist to dispatch (default: the master
        ``_METHODS`` surface).  Other planes — the serving-fleet router and
        its engine agents (serving/router.py) — reuse this hardened
        server (codec rejects, hostile-handshake accept loop, per-conn
        threads) by passing their own service object + whitelist.

        ``backlog``: the listen queue depth.  The Listener default (1) is
        fine for a training fleet whose workers dial once at staggered
        times, but a SERVING plane dials in bursts — per-request client
        connections arriving together overflow a 1-deep accept queue, and
        the dropped SYNs park on kernel retransmit timers (1s, 2s, 4s...)
        that read as multi-second routing latency.  The serving fleet
        passes a deeper queue still."""
        self.service = service
        self._methods = tuple(methods) if methods is not None else _METHODS
        self._authkey = authkey
        self._sleep = sleep  # injectable: tests drive the accept-loop backoff
        self._max_msg = max_message_bytes or _wire.default_max_bytes()
        self._listener = Listener(address, backlog=int(backlog),
                                  authkey=authkey)
        self.address = self._listener.address
        self._stop = False
        self._conns: List = []
        self._conns_lock = make_lock("master.Server._conns_lock")
        self._thread = threading.Thread(
            target=self._serve, name="paddle-master-accept", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn = self._listener.accept()
            except OSError as exc:
                if self._stop:
                    return  # the listener itself closed (Server.close)
                if isinstance(exc, ConnectionError):
                    # ConnectionResetError / BrokenPipeError from the auth
                    # handshake: ONE client hung up (RST mid-challenge) —
                    # per-client, same discipline as the clause below
                    continue
                if exc.errno is None:
                    # no errno = not a socket-level failure at all: the
                    # AUTH HANDSHAKE choked on garbage bytes — e.g.
                    # multiprocessing's "bad message length" when a port
                    # scanner's random length prefix blows its bound.
                    # Strictly per-client; treating it as a broken
                    # listener let ONE hostile connect close the master's
                    # port (found by the corrupt-frame storm drill)
                    continue
                if exc.errno in (
                    _errno.EMFILE, _errno.ENFILE,
                    _errno.ECONNABORTED, _errno.EINTR,
                ):
                    # transient: fd exhaustion under a dial storm (every
                    # timed-out client dial parks a socket) or an aborted
                    # connect.  The LISTENER is fine — bailing out here
                    # would leave the port bound-but-dead with clients
                    # queueing in the backlog until their dial deadlines
                    self._sleep(0.05)
                    continue
                # the listening socket itself is broken: close it so
                # clients get RST (fail fast into their retry loops)
                # instead of queueing in a dead backlog
                try:
                    self._listener.close()
                except OSError:
                    pass
                return
            except Exception:  # noqa: BLE001 — per-CLIENT handshake failure
                # A dialer that hung up mid-auth (its deadline fired and it
                # abandoned the socket — routine during a master bounce) or
                # presented a bad authkey surfaces here as EOFError /
                # AuthenticationError.  One client's failed handshake must
                # never kill the accept loop: the server would keep the
                # port bound (looking alive) while serving NOBODY — the
                # exact half-open state the client-side dial deadline
                # exists to escape.  Drop the connection, keep accepting.
                continue
            # hostile-network drills: when a net_* chaos point is armed the
            # accepted connection serves through the fault-injecting
            # transport (robustness/netem.py); unarmed this is a no-op
            conn = _netem.maybe_wrap(conn, role="server")
            with self._conns_lock:
                self._conns.append(conn)
            if self._stop:  # closed while accepting: don't serve it
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(
                target=self._handle, args=(conn,),
                name="paddle-master-conn", daemon=True,
            ).start()

    def _reject_frame(self, conn, exc: Exception) -> bool:
        """One codec rejection: count it, tell the client with a structured
        wire-reject reply (the request never executed, so the client's
        bounded retry re-sends it whole).  Returns False when the reply
        itself cannot be delivered — drop the connection then."""
        _wire.counters.incr("server_rejected_frames")
        _log.warning("master: rejected inbound frame: %s", exc)
        try:
            _wire.send_msg(
                conn, (False, {"__wire_reject__": str(exc)}), self._max_msg
            )
            return True
        except (OSError, ValueError, _wire.MasterWireError):
            return False

    def _reply(self, conn, ok: bool, result, seq) -> None:
        """Send one reply, echoing the request's correlation ``seq`` (the
        client discards stale/duplicated replies by it).  A reply the
        codec refuses — an unencodable or over-budget result — degrades to
        a structured application error instead of a wedged client."""
        reply = (ok, result) if seq is None else (ok, result, seq)
        try:
            _wire.send_msg(conn, reply, self._max_msg, label="server")
        except _wire.MasterWireError as exc:
            _wire.counters.incr("server_reply_rejected")
            fallback = (False, repr(exc))
            _wire.send_msg(
                conn, fallback if seq is None else fallback + (seq,),
                self._max_msg,
            )

    def _handle(self, conn) -> None:
        try:
            while not self._stop:  # deposed leader: stop serving stale state
                try:
                    msg = _wire.recv_msg(conn, self._max_msg, label="server")
                except _wire.WireOversizeError as exc:
                    # the transport refused the length prefix BEFORE
                    # allocating and closed the (now desynced) stream —
                    # count, log, drop this client; the listener keeps
                    # accepting
                    _wire.counters.incr("server_rejected_frames")
                    _wire.counters.incr("server_oversize_frames")
                    _log.warning("master: dropped connection: %s", exc)
                    return
                except _wire.MasterWireError as exc:
                    # corrupt/unknown-version frame inside an INTACT
                    # message boundary: stream sync is preserved by the
                    # transport's own framing, so reject the frame and
                    # keep serving the connection
                    if not self._reject_frame(conn, exc):
                        return
                    continue
                # requests are (method, args[, meta]); meta carries the obs
                # correlation id and the reply-matching seq.  A structurally
                # alien — but validly encoded — message is a reject, not a
                # crash (hostile peers send anything).
                if (not isinstance(msg, (tuple, list)) or len(msg) < 2
                        or not isinstance(msg[0], str)):
                    if not self._reject_frame(
                        conn, _wire.WireCorruptError(
                            f"request shape {type(msg).__name__} is not "
                            f"(method, args[, meta])"
                        )
                    ):
                        return
                    continue
                method, args = msg[0], msg[1]
                meta = msg[2] if len(msg) > 2 else None
                if not isinstance(meta, dict):
                    meta = None
                seq = meta.get("seq") if meta else None
                if method == "__close__":
                    return
                if method not in self._methods:
                    self._reply(conn, False, f"no such method {method}", seq)
                    continue
                # the server-side half of the skew-alignment pair: span
                # `rpc:<method>` with the CLIENT's correlation id — `trace
                # merge` pins its midpoint to the client span's midpoint
                with _obs.span(
                    "rpc:" + method, cat="master",
                    rpc=(meta or {}).get("rpc"),
                ):
                    try:
                        ok, result = True, getattr(self.service, method)(*args)
                    except Exception as exc:  # noqa: BLE001 — RPC boundary
                        ok, result = False, repr(exc)
                    self._reply(conn, ok, result, seq)
        except (EOFError, OSError, TypeError, AttributeError):
            # TypeError/AttributeError: Server.close() closed this conn while
            # recv() was blocked (multiprocessing nulls the handle mid-read)
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def close(self) -> None:
        """Stop accepting AND drop live per-connection handler threads — a
        deposed HA leader must not keep serving stale state to connected
        clients.  The accept loop is WOKEN with a dummy connection before
        the listener closes: a thread blocked in accept() holds the
        listening socket open past Listener.close(), which would keep the
        port bound and break a master restarting on its own address."""
        self._stop = True
        try:
            _ConnClient(tuple(self.address), authkey=self._authkey).close()
        except Exception:  # noqa: BLE001 — wake-up is best effort
            pass
        self._listener.close()
        self._thread.join(timeout=5)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class Client:
    """python/paddle/v2/master/client.py parity: set_dataset + next_record.

    `master` is either an in-process Service or a (host, port) address of a
    Server.  Records stream task-by-task; at a pass boundary next_record
    returns None once (like the reference's empty-record pass signal)."""

    def __init__(
        self,
        master,
        authkey: bytes = b"paddle-tpu",
        trainer_id: str = "0",
        reconnect_tries: int = 5,
        reconnect_backoff: float = 0.1,
        call_timeout_s: Optional[float] = 60.0,
        sleep=time.sleep,
        max_message_bytes: Optional[int] = None,
        methods: Optional[Tuple[str, ...]] = None,
    ):
        """``call_timeout_s`` is the per-RPC deadline (dial + reply): a
        call against a half-open socket — a master that bounced without an
        RST, a frozen leader — surfaces as :class:`MasterTimeoutError`
        instead of blocking forever.  ``None`` disables the deadline.
        ``max_message_bytes`` bounds frames BOTH ways (default: the
        ``rpc_max_message_mb`` flag)."""
        self.call_timeout_s = (
            None if call_timeout_s is None else float(call_timeout_s)
        )
        # the delegation surface __getattr__ exposes; other planes (the
        # serving-fleet router/engine RPC) pass their own whitelist
        self._methods = tuple(methods) if methods is not None else _METHODS
        self._sleep = sleep  # injectable: reconnect backoff + lease polls
        self._max_msg = max_message_bytes or _wire.default_max_bytes()
        self._seq = 0  # per-call correlation: stale replies discard by it
        if isinstance(master, Service):
            self._service = master
            self._conn = None
        else:
            self._service = None
            self._address = tuple(master)
            self._authkey = authkey
            self._conn = self._dial()
            self._conn_lock = make_lock("master.Client._conn_lock")
        self.reconnect_tries = max(int(reconnect_tries), 1)
        self.reconnect_backoff = float(reconnect_backoff)
        self.trainer_id = trainer_id
        self._records: List[bytes] = []
        self._pending_task = None  # (task_id, epoch) awaiting ack-on-drain
        self._last_renew = 0.0
        self.lease_renew_secs = 10.0  # renewal throttle ceiling
        self._renew_interval = self.lease_renew_secs

    def _dial(self):
        """Deadline-guarded dial, wrapped in the netem fault transport
        when a ``net_*`` chaos point is armed (a re-dial during an active
        partition stays partitioned — the link is down, not the socket)."""
        return _netem.maybe_wrap(
            _dial_with_deadline(
                self._address, self._authkey, self.call_timeout_s
            ),
            role="client",
        )

    def _timeout(self, msg: str) -> "MasterTimeoutError":
        """Tear down the (half-open) connection and build the deadline
        error for the caller to raise: a frozen peer stays frozen, so the
        socket is dead either way."""
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None
        return MasterTimeoutError(msg)

    def _call(self, method: str, *args):
        """One RPC.  Transient TRANSPORT failures (connection reset / EOF on
        the pipe — a master restarting, a dropped socket) get a short
        reconnect-retry with exponential backoff before surfacing as
        :class:`MasterTransportError`; the retried call is re-sent whole
        (every master method is idempotent-or-epoch-guarded, so an
        at-least-once duplicate is absorbed server-side).  Application
        errors surface as :class:`MasterRPCError` immediately — the master
        EXECUTED the call; retrying a deterministic failure is futile.

        Every remote call carries a DEADLINE (``call_timeout_s``): if the
        reply doesn't arrive in time — a half-open socket after a master
        bounce, a frozen leader — the connection is dropped and
        :class:`MasterTimeoutError` raises immediately (no in-client
        retry: a frozen peer stays frozen; the HA layer re-discovers the
        leader instead).  The abandoned call may still execute
        server-side, which the idempotent surface absorbs on retry.

        Hostile-network discipline: the request is wire-encoded ONCE up
        front — an unencodable or over-budget payload raises a structured
        :class:`~paddle_tpu.master_wire.MasterWireError` immediately
        (deterministic; retrying cannot shrink a gradient tree) — and the
        reply is matched by a per-call ``seq``: a duplicated or reordered
        delivery (netem drills, at-least-once retries) surfaces as a
        STALE reply that is discarded, never as a reply credited to the
        wrong call.  A corrupt reply frame, or the server's structured
        rejection of our (corrupted-in-flight) request, rides the same
        bounded reconnect-retry as a transport blip."""
        if self._service is not None:
            with _obs.span("rpc_call:" + method, cat="rpc"):
                return getattr(self._service, method)(*args)
        last_err: Optional[Exception] = None
        # the client-side half of the skew-alignment pair: the rpc id rides
        # the wire in the meta dict so the server span carries the SAME
        # correlation id; `seq` is the reply-matching correlation every
        # call carries
        rpc_id = _obs.next_rpc_id() if _obs.tracer.recording else None
        with self._conn_lock:
            # seq is minted UNDER the exchange lock: two threads sharing
            # this client must never carry the same seq, or a late/
            # duplicated reply could be credited to the wrong call —
            # the exact misattribution the correlation exists to prevent
            self._seq += 1
            seq = self._seq
            meta: Dict[str, Any] = {"seq": seq}
            if rpc_id is not None:
                meta["rpc"] = rpc_id
            # encode ONCE, outside the retry loop: WireTypeError/
            # WireOversizeError are deterministic and surface immediately
            # as the structured send-side bound (satellite: a multi-MB
            # tree no longer wedges against a frozen peer — it fails
            # fast, named)
            frame = _wire.encode_frame(
                _wire.encode_payload((method, args, meta)), self._max_msg
            )
            for attempt in range(self.reconnect_tries):
                try:
                    if self._conn is None:
                        self._conn = self._dial()
                    # the span covers ONLY the send->recv exchange (not
                    # the lock-queue wait or dial retries above): its
                    # midpoint is what `trace merge` pins the server
                    # handling span to, and client-side-only latencies
                    # would bias the skew estimate
                    with _obs.span(
                        "rpc_call:" + method, cat="rpc", rpc=rpc_id,
                    ):
                        try:
                            self._conn.send_bytes(frame)  # lock: allow[C304] _conn_lock serializes the whole RPC exchange by design; the poll deadline + SO_SNDTIMEO bound the hold
                            _wire.count_bytes("sent", len(frame), "client")
                        except BlockingIOError as exc:
                            # SO_SNDTIMEO fired: the peer stopped draining
                            # its socket mid-request (frozen master, full
                            # buffer)
                            raise self._timeout(
                                f"master RPC {method}: request stalled "
                                f"mid-send (frozen master)"
                            ) from exc
                        ok, result = self._recv_reply(method, seq)
                    break
                except MasterTimeoutError:
                    raise
                except (
                    _wire.MasterWireError, ConnectionError, EOFError, OSError,
                ) as exc:
                    last_err = exc
                    if self._conn is not None:
                        try:
                            self._conn.close()
                        except OSError:
                            pass
                        self._conn = None
                    if attempt + 1 >= self.reconnect_tries:
                        raise MasterTransportError(
                            f"master RPC {method}: transport failed after "
                            f"{self.reconnect_tries} attempt(s): {exc!r}"
                        ) from exc
                    # backoff keeps _conn_lock deliberately: a second
                    # caller dialing concurrently would race the fresh
                    # connection (injected sleep: tests drive it)
                    self._sleep(self.reconnect_backoff * (2 ** attempt))
        if not ok:
            raise MasterRPCError(f"master RPC {method} failed: {result}")
        return result

    def _recv_reply(self, method: str, seq: int) -> Tuple[bool, Any]:
        """Wait out ONE reply matching ``seq`` under the per-call
        deadline.  Stale replies (an abandoned call's late answer, a
        netem-duplicated delivery) are counted and discarded; a corrupt
        frame or the server's structured wire-reject raises the
        (retryable) wire error.  Only reached while ``_conn_lock`` is
        held by ``_call``."""
        deadline = (
            None if self.call_timeout_s is None
            else time.monotonic() + self.call_timeout_s
        )
        discarded = 0
        while True:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._conn.poll(remaining):
                    raise self._timeout(
                        f"master RPC {method}: no reply in "
                        f"{self.call_timeout_s}s (half-open socket "
                        f"or frozen master); the call may have "
                        f"executed"
                    )
            try:
                buf = self._conn.recv_bytes(self._max_msg)  # lock: allow[C304] same intentional hold: one in-flight RPC per connection, bounded by SO_RCVTIMEO
            except BlockingIOError as exc:
                # SO_RCVTIMEO fired mid-message: the peer froze
                # after sending a PARTIAL reply — past poll()'s
                # first-byte deadline, so surface the same way
                raise self._timeout(
                    f"master RPC {method}: reply stalled "
                    f"mid-message (frozen master); the call "
                    f"may have executed"
                ) from exc
            except OSError as exc:
                if "bad message length" in str(exc):
                    # recv-side size bound: refused before allocation
                    _wire.counters.incr("client_rejected_frames")
                    _wire.counters.incr("client_oversize_frames")
                    raise _wire.WireOversizeError(
                        f"master RPC {method}: reply exceeds the "
                        f"{self._max_msg}-byte bound (flag "
                        f"rpc_max_message_mb)"
                    ) from exc
                raise
            _wire.count_bytes("recv", len(buf), "client")
            try:
                resp = _wire.decode_payload(
                    _wire.decode_frame(buf, self._max_msg)
                )
            except _wire.MasterWireError:
                _wire.counters.incr("client_rejected_frames")
                raise
            if not isinstance(resp, (tuple, list)) or len(resp) < 2:
                _wire.counters.incr("client_rejected_frames")
                raise _wire.WireCorruptError(
                    f"master RPC {method}: reply shape "
                    f"{type(resp).__name__} is not (ok, result[, seq])"
                )
            if (len(resp) == 2 and resp[0] is False
                    and isinstance(resp[1], dict)
                    and "__wire_reject__" in resp[1]):
                # the server's codec refused OUR frame (corrupted in
                # flight): the call never executed — retry re-sends it
                raise _wire.WireCorruptError(
                    f"master RPC {method}: server rejected request "
                    f"frame: {resp[1]['__wire_reject__']}"
                )
            if len(resp) >= 3 and resp[2] != seq:
                # a duplicated/reordered delivery, or an abandoned
                # call's late reply: never credit it to THIS call
                _wire.counters.incr("stale_replies_discarded")
                discarded += 1
                if discarded > 64:
                    raise _wire.WireCorruptError(
                        f"master RPC {method}: {discarded} consecutive "
                        f"stale replies (reply stream desynced)"
                    )
                continue
            return bool(resp[0]), resp[1]

    # -- surface ---------------------------------------------------------
    def set_dataset(self, patterns: Sequence[str]) -> int:
        return self._call("set_dataset", list(patterns))

    def request_save_model(self, block_secs: float = 60.0) -> bool:
        return self._call("request_save_model", self.trainer_id, block_secs)

    def start_new_pass(self, target_pass: Optional[int] = None,
                       worker_id: Optional[str] = None) -> int:
        return self._call("start_new_pass", target_pass, worker_id)

    def __getattr__(self, name: str):
        """Every other RPC method (the elastic cluster surface — get_task,
        task_finished(task, epoch, result), register_worker/heartbeat,
        fence_arrive/fence_status, pass_results, requeue_unresulted,
        stats, ...) delegates positionally straight from ``_METHODS`` —
        ONE definition instead of a hand-kept mirror per client class.
        Signatures/semantics are the Service methods'."""
        if name != "_methods" and name in self._methods:
            return lambda *args: self._call(name, *args)
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}"
        )

    def next_record(self) -> Optional[bytes]:
        """The next record of the current task, fetching a new task when the
        current one drains; None exactly at a pass boundary."""
        if self._records and self._pending_task is not None:
            # Renew the held lease while the trainer drains (throttled to a
            # fraction of the server's lease timeout): a consumer slower than
            # the lease timeout must not trip the failure/discard path.  A
            # failed renewal means the task already expired and was re-served
            # elsewhere — keep serving the buffer (at-least-once duplicates),
            # the epoch-guarded ack below is then a harmless no-op.
            now = time.time()
            if now - self._last_renew >= self._renew_interval:
                self._last_renew = now
                self._call("renew_lease", *self._pending_task)
        while not self._records:
            # Consume-then-ack (at-least-once, reference go/master client
            # semantics): the previous task is finished only once every one
            # of its records has been handed to the trainer, so a crash
            # mid-consumption re-serves the task instead of losing it.
            if self._pending_task is not None:
                self._call("task_finished", *self._pending_task)
                self._pending_task = None
            got = self._call("get_task")
            if got is None:
                return None
            if got == "wait":  # other workers hold the remaining leases
                self._sleep(0.01)
                continue
            fetched: List[bytes] = []
            try:
                for c in got["task"]["chunks"]:
                    with recordio.Reader(c["path"], offset=c["offset"]) as r:
                        for _ in range(c["n_records"]):
                            rec = r.next()
                            if rec is None:
                                break
                            fetched.append(rec)
            except IOError:
                self._call("task_failed", got["task"]["task_id"], got["epoch"])
                continue
            # Lease is held until drain (renewed above while consuming); a
            # crash mid-consumption re-serves the task (duplicates are
            # possible, loss is not).
            self._pending_task = (got["task"]["task_id"], got["epoch"])
            self._last_renew = time.time()
            # Renew well before the server-side lease expires.
            self._renew_interval = min(
                self.lease_renew_secs, got.get("timeout_s", 60.0) / 3.0
            )
            self._records = fetched
        return self._records.pop(0)

    def reader(self):
        """A reader-creator over next_record for the v2 trainer: one call =
        one pass."""
        return reader_over(self.next_record)

    def close(self) -> None:
        # Release a held lease: ack if the buffer drained, otherwise hand the
        # task back (no failure event) so the records re-serve this pass
        # instead of expiring into the failure/discard path.
        if self._pending_task is not None:
            try:
                if self._records:
                    self._call("task_returned", *self._pending_task)
                else:
                    self._call("task_finished", *self._pending_task)
            except (RuntimeError, BrokenPipeError, OSError, EOFError):
                pass
            self._pending_task = None
            self._records = []
        if self._conn is not None:
            try:
                _wire.send_msg(self._conn, ("__close__", ()), self._max_msg)
            except (BrokenPipeError, OSError, _wire.MasterWireError):
                pass
            self._conn.close()
