"""Elastic master — fault-tolerant task-queue data dispatch (reference:
go/master/service.go, the Go master the v2 python API reaches through
python/paddle/v2/master/client.py).

The reference partitions recordio chunks into tasks and serves them to
stateless trainers over RPC with etcd-snapshotted todo/pending/done/failed
queues; a timed-out pending task is requeued, and a task failing more than
`failure_max` times is discarded (service.go:80-459).  This implementation
keeps the exact queue semantics but is etcd-free: queue snapshots go to a
JSON file (atomic rename) and leadership is a filesystem lease — the TPU
deployment model has a single coordinator host per pod slice, so file-lease
is the idiomatic replacement for etcd election.

Pieces:
  * ``Service``    — the queue state machine (thread-safe, in-process).
  * ``Server``     — serves a Service over ``multiprocessing.connection``
                     (a real process/network boundary like the Go RPC server).
  * ``Client``     — ``set_dataset / next_record / ...`` parity with
                     python/paddle/v2/master/client.py; works against an
                     in-process Service or a remote Server address.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
import threading
import time
from multiprocessing.connection import Client as _ConnClient, Listener
from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.io import recordio

__all__ = [
    "Service", "Server", "Client", "MasterRPCError", "MasterTransportError",
]


class MasterRPCError(RuntimeError):
    """The master executed the call and reported an application error —
    distinct from transport failures so HA clients do not reconnect-retry
    deterministic errors."""


class MasterTransportError(ConnectionError):
    """The TRANSPORT failed (broken pipe / EOF / refused) and the client's
    short reconnect-retry window was exhausted — the call may or may not
    have executed.  Subclasses ConnectionError so HA wrappers (master_ha.
    HAClient) treat it as 'leader gone, re-discover', never as an
    application error."""


@dataclasses.dataclass
class Task:
    task_id: int
    chunks: List[recordio.Chunk]
    epoch: int = 0  # failure count (reference service.go Task.Epoch)

    def to_json(self):
        return {
            "task_id": self.task_id,
            "epoch": self.epoch,
            "chunks": [
                {"path": c.path, "offset": c.offset, "n_records": c.n_records}
                for c in self.chunks
            ],
        }

    @staticmethod
    def from_json(d):
        return Task(
            d["task_id"],
            [recordio.Chunk(c["path"], c["offset"], c["n_records"]) for c in d["chunks"]],
            d["epoch"],
        )


class Service:
    """Queue state machine: todo / pending / done / failed (reference
    go/master/service.go:80)."""

    def __init__(
        self,
        snapshot_path: Optional[str] = None,
        chunks_per_task: int = 8,
        timeout_s: float = 60.0,
        failure_max: int = 3,
        auto_rotate: bool = True,
        snapshot_min_interval_s: float = 1.0,
        clock=time.time,
    ):
        """auto_rotate=True mirrors the reference: the moment a pass drains,
        done tasks recycle into todo and other trainers stream straight into
        the next pass (pass-end is a per-client observation, service.go:404).
        auto_rotate=False holds the pass boundary until start_new_pass() —
        the synchronized-pass mode a sync-SGD trainer wants."""
        self._lock = threading.RLock()
        self._clock = clock  # injectable for deterministic lease tests
        self.chunks_per_task = chunks_per_task
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.auto_rotate = auto_rotate
        self.snapshot_path = snapshot_path
        self.snapshot_min_interval_s = snapshot_min_interval_s
        self._last_snapshot = 0.0
        self._flush_timer: Optional[threading.Timer] = None
        self.todo: List[Task] = []
        self.pending: Dict[int, Tuple[Task, float]] = {}  # id -> (task, deadline)
        self.done: List[Task] = []
        self.discarded: List[Task] = []
        self.fail_events = 0
        self.pass_id = 0
        self._save_holder: Optional[Tuple[str, float]] = None
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset ---------------------------------------------------------
    def set_dataset(self, patterns: Sequence[str]) -> int:
        """Partition the recordio files into tasks (reference
        service.go:105 partition()).  Idempotent: only the first caller wins,
        like the reference's SetDataset."""
        with self._lock:
            if self.todo or self.pending or self.done:
                return self.n_tasks()
            chunks: List[recordio.Chunk] = []
            for pat in patterns:
                for path in sorted(_glob.glob(pat)):
                    chunks.extend(recordio.scan_chunks(path))
            tasks = []
            for i in range(0, len(chunks), self.chunks_per_task):
                tasks.append(Task(len(tasks), chunks[i : i + self.chunks_per_task]))
            self.todo = tasks
            self._snapshot(force=True)
            return len(tasks)

    def n_tasks(self) -> int:
        with self._lock:
            return len(self.todo) + len(self.pending) + len(self.done)

    # -- task lifecycle --------------------------------------------------
    def get_task(self):
        """Pop a todo task into pending with a lease deadline (reference
        service.go:362 GetTask).  Returns the task dict, the string "wait"
        when all remaining tasks are leased to other workers (mid-pass
        starvation), or None at a pass boundary."""
        with self._lock:
            self._requeue_expired()
            if not self.todo and not self.pending and self.done:
                if not self.auto_rotate:
                    return None  # hold the barrier until start_new_pass()
                self._rotate_pass()
                return None  # signal pass boundary to the observing client
            if not self.todo:
                return "wait" if self.pending else None
            task = self.todo.pop(0)
            self.pending[task.task_id] = (task, self._clock() + self.timeout_s)
            self._snapshot()
            return {
                "task": task.to_json(),
                "epoch": task.epoch,
                "timeout_s": self.timeout_s,
            }

    def _rotate_pass(self) -> None:
        """Recycle done → todo; epochs reset so past failures don't carry."""
        self.todo = self.done
        for t in self.todo:
            t.epoch = 0
        self.done = []
        self.pass_id += 1
        self._snapshot(force=True)

    def start_new_pass(self) -> int:
        """Explicit pass barrier release (auto_rotate=False mode)."""
        with self._lock:
            if not self.todo and not self.pending and self.done:
                self._rotate_pass()
            return self.pass_id

    def renew_lease(self, task_id: int, epoch: int) -> bool:
        """Extend a pending task's lease (consume-then-ack keeps the lease
        open while the trainer drains records; renewal prevents a slow
        consumer's task from expiring into the failure path).  The epoch
        guard rejects a stale holder whose task was already re-served."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            self.pending[task_id] = (ent[0], self._clock() + self.timeout_s)
            return True

    def task_finished(self, task_id: int, epoch: Optional[int] = None) -> bool:
        """epoch (when given) guards against a stale holder acking a task
        that expired and was re-served at a higher epoch — same discipline
        as task_failed (reference service.go:404 checks task epoch)."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or (epoch is not None and ent[0].epoch != epoch):
                return False
            del self.pending[task_id]
            self.done.append(ent[0])
            self._snapshot()
            return True

    def task_failed(self, task_id: int, epoch: int) -> bool:
        """(reference service.go:442 TaskFailed → processFailedTask:308)"""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            del self.pending[task_id]
            self._process_failed(ent[0])
            self._snapshot()
            return True

    def task_returned(self, task_id: int, epoch: int) -> bool:
        """Graceful give-back: a client closing with unconsumed records hands
        its task back to the todo queue WITHOUT burning a failure event —
        deliberate abandonment (early stop, capped test pass) is not a crash,
        and must not walk the task toward the failure_max discard."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            del self.pending[task_id]
            self.todo.append(ent[0])
            self._snapshot()
            return True

    def _process_failed(self, task: Task) -> None:
        """epoch++, discard past failure_max, else requeue (service.go:308)."""
        self.fail_events += 1
        task.epoch += 1
        if task.epoch >= self.failure_max:
            self.discarded.append(task)  # discard (service.go:336)
        else:
            self.todo.append(task)

    def _requeue_expired(self) -> None:
        now = self._clock()
        expired = [tid for tid, (_, dl) in self.pending.items() if dl < now]
        for tid in expired:
            task, _ = self.pending.pop(tid)
            self._process_failed(task)

    # -- save-model arbitration (reference service.go:461-497) -----------
    def request_save_model(self, trainer_id: str, block_secs: float) -> bool:
        """Exactly one trainer in each window gets True."""
        with self._lock:
            now = self._clock()
            if self._save_holder and self._save_holder[1] > now:
                return self._save_holder[0] == trainer_id
            self._save_holder = (trainer_id, now + block_secs)
            return True

    # -- snapshot / recover (reference service.go:165-273, etcd → file) --
    def fence(self) -> None:
        """Stop this (deposed) Service from ever writing the shared snapshot
        again and cancel any pending debounced flush — a new leader owns the
        file now (the etcd design gets this for free from leases on keys)."""
        with self._lock:
            self.snapshot_path = None
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None

    def _snapshot(self, force: bool = False) -> None:
        """Debounced: per-task transitions at most one write per
        snapshot_min_interval_s; a skipped write is flushed by a timer so the
        last transition of a burst always reaches disk.  Structural changes
        (set_dataset, pass rotation) always write."""
        if not self.snapshot_path:
            return
        now = time.time()
        if not force and now - self._last_snapshot < self.snapshot_min_interval_s:
            if self._flush_timer is None:
                t = threading.Timer(self.snapshot_min_interval_s, self._flush)
                t.daemon = True
                self._flush_timer = t
                t.start()
            return
        self._last_snapshot = now
        self._write_snapshot()

    def _flush(self) -> None:
        with self._lock:
            self._flush_timer = None
            if not self.snapshot_path:
                return  # fenced between schedule and fire
            self._last_snapshot = time.time()
            self._write_snapshot()

    def _write_snapshot(self) -> None:
        state = {
            "pass_id": self.pass_id,
            "todo": [t.to_json() for t in self.todo],
            "pending": [
                {"task": t.to_json(), "deadline": dl}
                for (t, dl) in self.pending.values()
            ],
            "done": [t.to_json() for t in self.done],
            "discarded": [t.to_json() for t in self.discarded],
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)

    def _recover(self) -> None:
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self.pass_id = state["pass_id"]
        self.todo = [Task.from_json(t) for t in state["todo"]]
        self.done = [Task.from_json(t) for t in state["done"]]
        self.discarded = [Task.from_json(t) for t in state.get("discarded", [])]
        # pending leases do not survive a master restart: requeue immediately
        # (the reference instead waits for timeout; restart is the slow path)
        for ent in state["pending"]:
            self.todo.append(Task.from_json(ent["task"]))


def reader_over(next_record_fn):
    """Reader-creator over a next_record callable: one call = one pass
    (shared by Client and master_ha.HAClient)."""

    def _reader():
        while True:
            rec = next_record_fn()
            if rec is None:
                return
            yield rec

    return _reader


# ---------------------------------------------------------------------------
# RPC layer
# ---------------------------------------------------------------------------

_METHODS = ("set_dataset", "get_task", "task_finished", "task_failed",
            "task_returned", "renew_lease", "request_save_model", "n_tasks",
            "start_new_pass")


class Server:
    """Serve a Service over multiprocessing.connection — the process/network
    boundary of the Go master's net/rpc server."""

    def __init__(self, service: Service, address=("127.0.0.1", 0), authkey=b"paddle-tpu"):
        self.service = service
        self._authkey = authkey
        self._listener = Listener(address, authkey=authkey)
        self.address = self._listener.address
        self._stop = False
        self._conns: List = []
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn = self._listener.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.append(conn)
            if self._stop:  # closed while accepting: don't serve it
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn) -> None:
        try:
            while not self._stop:  # deposed leader: stop serving stale state
                method, args = conn.recv()
                if method == "__close__":
                    return
                if method not in _METHODS:
                    conn.send((False, f"no such method {method}"))
                    continue
                try:
                    conn.send((True, getattr(self.service, method)(*args)))
                except Exception as exc:  # noqa: BLE001 — RPC boundary
                    conn.send((False, repr(exc)))
        except (EOFError, OSError, TypeError, AttributeError):
            # TypeError/AttributeError: Server.close() closed this conn while
            # recv() was blocked (multiprocessing nulls the handle mid-read)
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def close(self) -> None:
        """Stop accepting AND drop live per-connection handler threads — a
        deposed HA leader must not keep serving stale state to connected
        clients.  The accept loop is WOKEN with a dummy connection before
        the listener closes: a thread blocked in accept() holds the
        listening socket open past Listener.close(), which would keep the
        port bound and break a master restarting on its own address."""
        self._stop = True
        try:
            _ConnClient(tuple(self.address), authkey=self._authkey).close()
        except Exception:  # noqa: BLE001 — wake-up is best effort
            pass
        self._listener.close()
        self._thread.join(timeout=5)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class Client:
    """python/paddle/v2/master/client.py parity: set_dataset + next_record.

    `master` is either an in-process Service or a (host, port) address of a
    Server.  Records stream task-by-task; at a pass boundary next_record
    returns None once (like the reference's empty-record pass signal)."""

    def __init__(
        self,
        master,
        authkey: bytes = b"paddle-tpu",
        trainer_id: str = "0",
        reconnect_tries: int = 5,
        reconnect_backoff: float = 0.1,
    ):
        if isinstance(master, Service):
            self._service = master
            self._conn = None
        else:
            self._service = None
            self._address = tuple(master)
            self._authkey = authkey
            self._conn = _ConnClient(self._address, authkey=authkey)
            self._conn_lock = threading.Lock()
        self.reconnect_tries = max(int(reconnect_tries), 1)
        self.reconnect_backoff = float(reconnect_backoff)
        self.trainer_id = trainer_id
        self._records: List[bytes] = []
        self._pending_task = None  # (task_id, epoch) awaiting ack-on-drain
        self._last_renew = 0.0
        self.lease_renew_secs = 10.0  # renewal throttle ceiling
        self._renew_interval = self.lease_renew_secs

    def _call(self, method: str, *args):
        """One RPC.  Transient TRANSPORT failures (connection reset / EOF on
        the pipe — a master restarting, a dropped socket) get a short
        reconnect-retry with exponential backoff before surfacing as
        :class:`MasterTransportError`; the retried call is re-sent whole
        (every master method is idempotent-or-epoch-guarded, so an
        at-least-once duplicate is absorbed server-side).  Application
        errors surface as :class:`MasterRPCError` immediately — the master
        EXECUTED the call; retrying a deterministic failure is futile."""
        if self._service is not None:
            return getattr(self._service, method)(*args)
        last_err: Optional[Exception] = None
        with self._conn_lock:
            for attempt in range(self.reconnect_tries):
                try:
                    if self._conn is None:
                        self._conn = _ConnClient(
                            self._address, authkey=self._authkey
                        )
                    self._conn.send((method, args))
                    ok, result = self._conn.recv()
                    break
                except (ConnectionError, EOFError, OSError) as exc:
                    last_err = exc
                    if self._conn is not None:
                        try:
                            self._conn.close()
                        except OSError:
                            pass
                        self._conn = None
                    if attempt + 1 >= self.reconnect_tries:
                        raise MasterTransportError(
                            f"master RPC {method}: transport failed after "
                            f"{self.reconnect_tries} attempt(s): {exc!r}"
                        ) from exc
                    time.sleep(self.reconnect_backoff * (2 ** attempt))
        if not ok:
            raise MasterRPCError(f"master RPC {method} failed: {result}")
        return result

    # -- surface ---------------------------------------------------------
    def set_dataset(self, patterns: Sequence[str]) -> int:
        return self._call("set_dataset", list(patterns))

    def request_save_model(self, block_secs: float = 60.0) -> bool:
        return self._call("request_save_model", self.trainer_id, block_secs)

    def start_new_pass(self) -> int:
        return self._call("start_new_pass")

    def next_record(self) -> Optional[bytes]:
        """The next record of the current task, fetching a new task when the
        current one drains; None exactly at a pass boundary."""
        if self._records and self._pending_task is not None:
            # Renew the held lease while the trainer drains (throttled to a
            # fraction of the server's lease timeout): a consumer slower than
            # the lease timeout must not trip the failure/discard path.  A
            # failed renewal means the task already expired and was re-served
            # elsewhere — keep serving the buffer (at-least-once duplicates),
            # the epoch-guarded ack below is then a harmless no-op.
            now = time.time()
            if now - self._last_renew >= self._renew_interval:
                self._last_renew = now
                self._call("renew_lease", *self._pending_task)
        while not self._records:
            # Consume-then-ack (at-least-once, reference go/master client
            # semantics): the previous task is finished only once every one
            # of its records has been handed to the trainer, so a crash
            # mid-consumption re-serves the task instead of losing it.
            if self._pending_task is not None:
                self._call("task_finished", *self._pending_task)
                self._pending_task = None
            got = self._call("get_task")
            if got is None:
                return None
            if got == "wait":  # other workers hold the remaining leases
                time.sleep(0.01)
                continue
            fetched: List[bytes] = []
            try:
                for c in got["task"]["chunks"]:
                    with recordio.Reader(c["path"], offset=c["offset"]) as r:
                        for _ in range(c["n_records"]):
                            rec = r.next()
                            if rec is None:
                                break
                            fetched.append(rec)
            except IOError:
                self._call("task_failed", got["task"]["task_id"], got["epoch"])
                continue
            # Lease is held until drain (renewed above while consuming); a
            # crash mid-consumption re-serves the task (duplicates are
            # possible, loss is not).
            self._pending_task = (got["task"]["task_id"], got["epoch"])
            self._last_renew = time.time()
            # Renew well before the server-side lease expires.
            self._renew_interval = min(
                self.lease_renew_secs, got.get("timeout_s", 60.0) / 3.0
            )
            self._records = fetched
        return self._records.pop(0)

    def reader(self):
        """A reader-creator over next_record for the v2 trainer: one call =
        one pass."""
        return reader_over(self.next_record)

    def close(self) -> None:
        # Release a held lease: ack if the buffer drained, otherwise hand the
        # task back (no failure event) so the records re-serve this pass
        # instead of expiring into the failure/discard path.
        if self._pending_task is not None:
            try:
                if self._records:
                    self._call("task_returned", *self._pending_task)
                else:
                    self._call("task_finished", *self._pending_task)
            except (RuntimeError, BrokenPipeError, OSError, EOFError):
                pass
            self._pending_task = None
            self._records = []
        if self._conn is not None:
            try:
                self._conn.send(("__close__", ()))
            except (BrokenPipeError, OSError):
                pass
            self._conn.close()
