"""Elastic master — fault-tolerant task-queue data dispatch (reference:
go/master/service.go, the Go master the v2 python API reaches through
python/paddle/v2/master/client.py).

The reference partitions recordio chunks into tasks and serves them to
stateless trainers over RPC with etcd-snapshotted todo/pending/done/failed
queues; a timed-out pending task is requeued, and a task failing more than
`failure_max` times is discarded (service.go:80-459).  This implementation
keeps the exact queue semantics but is etcd-free: queue snapshots go to a
JSON file (atomic rename) and leadership is a filesystem lease — the TPU
deployment model has a single coordinator host per pod slice, so file-lease
is the idiomatic replacement for etcd election.

Pieces:
  * ``Service``    — the queue state machine (thread-safe, in-process).
  * ``Server``     — serves a Service over ``multiprocessing.connection``
                     (a real process/network boundary like the Go RPC server).
  * ``Client``     — ``set_dataset / next_record / ...`` parity with
                     python/paddle/v2/master/client.py; works against an
                     in-process Service or a remote Server address.

Elastic cluster plane (the scale-out completion of the Go master's
fault-tolerance model, arXiv:1605.08695 §4.4):
  * worker registry — ``register_worker``/``heartbeat`` leases, pruned by
    the same clock discipline as task leases; a dead worker's pending task
    leases requeue to survivors immediately (the etcd-lease-expiry path of
    go/master/service.go, minus etcd).
  * pass fence — ``fence_arrive``/``fence_status``: a barrier over the LIVE
    membership, so a worker that died (and was pruned) never wedges the
    pass boundary.
  * result plane — ``task_finished(task_id, epoch, result)`` attaches a
    per-task payload (the epoch guard rejects zombie owners);
    ``pass_results`` hands the full map back so every worker reduces the
    pass deterministically in task-id order (trainer/elastic.py).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
import threading
import time
from multiprocessing.connection import Client as _ConnClient, Listener
from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.io import recordio

__all__ = [
    "Service", "Server", "Client", "MasterRPCError", "MasterTransportError",
]


class MasterRPCError(RuntimeError):
    """The master executed the call and reported an application error —
    distinct from transport failures so HA clients do not reconnect-retry
    deterministic errors."""


class MasterTransportError(ConnectionError):
    """The TRANSPORT failed (broken pipe / EOF / refused) and the client's
    short reconnect-retry window was exhausted — the call may or may not
    have executed.  Subclasses ConnectionError so HA wrappers (master_ha.
    HAClient) treat it as 'leader gone, re-discover', never as an
    application error."""


@dataclasses.dataclass
class Task:
    task_id: int
    chunks: List[recordio.Chunk]
    epoch: int = 0  # failure count (reference service.go Task.Epoch)

    def to_json(self):
        return {
            "task_id": self.task_id,
            "epoch": self.epoch,
            "chunks": [
                {"path": c.path, "offset": c.offset, "n_records": c.n_records}
                for c in self.chunks
            ],
        }

    @staticmethod
    def from_json(d):
        return Task(
            d["task_id"],
            [recordio.Chunk(c["path"], c["offset"], c["n_records"]) for c in d["chunks"]],
            d["epoch"],
        )


class Service:
    """Queue state machine: todo / pending / done / failed (reference
    go/master/service.go:80)."""

    def __init__(
        self,
        snapshot_path: Optional[str] = None,
        chunks_per_task: int = 8,
        timeout_s: float = 60.0,
        failure_max: int = 3,
        auto_rotate: bool = True,
        snapshot_min_interval_s: float = 1.0,
        clock=time.time,
        worker_timeout_s: float = 10.0,
    ):
        """auto_rotate=True mirrors the reference: the moment a pass drains,
        done tasks recycle into todo and other trainers stream straight into
        the next pass (pass-end is a per-client observation, service.go:404).
        auto_rotate=False holds the pass boundary until start_new_pass() —
        the synchronized-pass mode a sync-SGD trainer wants."""
        self._lock = threading.RLock()
        self._clock = clock  # injectable for deterministic lease tests
        self.chunks_per_task = chunks_per_task
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.auto_rotate = auto_rotate
        self.snapshot_path = snapshot_path
        self.snapshot_min_interval_s = snapshot_min_interval_s
        self._last_snapshot = 0.0
        self._flush_timer: Optional[threading.Timer] = None
        self.todo: List[Task] = []
        # id -> (task, lease deadline, owner worker id or None)
        self.pending: Dict[int, Tuple[Task, float, Optional[str]]] = {}
        self.done: List[Task] = []
        self.discarded: List[Task] = []
        self.fail_events = 0
        self.pass_id = 0
        self._save_holder: Optional[Tuple[str, float]] = None
        # -- elastic cluster plane (registry / fences / results) ----------
        self.worker_timeout_s = worker_timeout_s
        self.workers: Dict[str, float] = {}  # worker id -> heartbeat deadline
        # pass_id -> {task_id: payload}; only the trailing passes are
        # retained (a slow or late-joining worker may still need pass P's
        # map while P+1 streams)
        self.results: Dict[int, Dict[int, Any]] = {}
        self._pass_done: Dict[int, int] = {}  # pass -> done count at rotation
        # fence id -> {"arrived": set, "released": None | frozen info dict}
        self.fences: Dict[str, Dict[str, Any]] = {}
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset ---------------------------------------------------------
    def set_dataset(self, patterns: Sequence[str]) -> int:
        """Partition the recordio files into tasks (reference
        service.go:105 partition()).  Idempotent: only the first caller wins,
        like the reference's SetDataset."""
        with self._lock:
            if self.todo or self.pending or self.done:
                return self.n_tasks()
            chunks: List[recordio.Chunk] = []
            for pat in patterns:
                for path in sorted(_glob.glob(pat)):
                    chunks.extend(recordio.scan_chunks(path))
            tasks = []
            for i in range(0, len(chunks), self.chunks_per_task):
                tasks.append(Task(len(tasks), chunks[i : i + self.chunks_per_task]))
            self.todo = tasks
            self._snapshot(force=True)
            return len(tasks)

    def n_tasks(self) -> int:
        with self._lock:
            return len(self.todo) + len(self.pending) + len(self.done)

    # -- task lifecycle --------------------------------------------------
    def get_task(self, worker_id: Optional[str] = None):
        """Pop a todo task into pending with a lease deadline (reference
        service.go:362 GetTask).  Returns the task dict, the string "wait"
        when all remaining tasks are leased to other workers (mid-pass
        starvation), or None at a pass boundary.  ``worker_id`` (when the
        caller is a registered elastic worker) records the lease owner so
        a pruned worker's leases requeue without waiting out the per-task
        timeout."""
        with self._lock:
            self._prune_workers()
            self._requeue_expired()
            if worker_id is not None:
                # a polling worker is alive by definition: auto-(re)register
                # even if the prune just expired it (prune targets SILENT
                # workers — hung or dead — which never reach this line)
                self.workers[worker_id] = self._clock() + self.worker_timeout_s
            if not self.todo and not self.pending and self.done:
                if not self.auto_rotate:
                    return None  # hold the barrier until start_new_pass()
                self._rotate_pass()
                return None  # signal pass boundary to the observing client
            if not self.todo:
                return "wait" if self.pending else None
            task = self.todo.pop(0)
            self.pending[task.task_id] = (
                task, self._clock() + self.timeout_s, worker_id
            )
            self._snapshot()
            return {
                "task": task.to_json(),
                "epoch": task.epoch,
                "timeout_s": self.timeout_s,
                # which pass this task belongs to: an elastic worker that
                # believes it is on an earlier pass detects the skew here
                # and catches up BEFORE computing with stale parameters
                "pass_id": self.pass_id,
            }

    def _rotate_pass(self) -> None:
        """Recycle done → todo; epochs reset so past failures don't carry."""
        # freeze the completed pass's done count: late joiners use it to
        # verify a retained result map is COMPLETE before replay-applying it
        self._pass_done[self.pass_id] = len(self.done)
        self.todo = self.done
        for t in self.todo:
            t.epoch = 0
        self.done = []
        self.pass_id += 1
        # retain only the trailing passes' result maps (a slow worker may
        # still be fetching pass P's results while P+1 streams)
        for p in [p for p in self.results if p < self.pass_id - 2]:
            del self.results[p]
        for p in [p for p in self._pass_done if p < self.pass_id - 2]:
            del self._pass_done[p]
        self._snapshot(force=True)

    def start_new_pass(self, target_pass: Optional[int] = None) -> int:
        """Explicit pass barrier release (auto_rotate=False mode).

        ``target_pass`` makes the release idempotent for a fleet: the pass
        rotates only while ``pass_id < target_pass``, so a straggler that
        calls ``start_new_pass(p+1)`` after a fast worker already drained
        pass p+1 cannot double-rotate the queue past it."""
        with self._lock:
            if (
                not self.todo and not self.pending and self.done
                and (target_pass is None or self.pass_id < target_pass)
            ):
                self._rotate_pass()
            return self.pass_id

    def renew_lease(self, task_id: int, epoch: int) -> bool:
        """Extend a pending task's lease (consume-then-ack keeps the lease
        open while the trainer drains records; renewal prevents a slow
        consumer's task from expiring into the failure path).  The epoch
        guard rejects a stale holder whose task was already re-served."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            self.pending[task_id] = (
                ent[0], self._clock() + self.timeout_s, ent[2]
            )
            return True

    def task_finished(
        self, task_id: int, epoch: Optional[int] = None, result: Any = None
    ) -> bool:
        """epoch (when given) guards against a stale holder acking a task
        that expired and was re-served at a higher epoch — same discipline
        as task_failed (reference service.go:404 checks task epoch).

        ``result`` (elastic workers): the task's reduction payload — e.g. a
        gradient-contribution tree — stored under the current pass for
        ``pass_results``.  A rejected (zombie) ack never stores its result,
        so the surviving re-computation's bits win."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or (epoch is not None and ent[0].epoch != epoch):
                return False
            del self.pending[task_id]
            self.done.append(ent[0])
            if result is not None:
                self.results.setdefault(self.pass_id, {})[task_id] = result
            self._snapshot()
            return True

    def task_failed(self, task_id: int, epoch: int) -> bool:
        """(reference service.go:442 TaskFailed → processFailedTask:308)"""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            del self.pending[task_id]
            self._process_failed(ent[0])
            self._snapshot()
            return True

    def task_returned(self, task_id: int, epoch: int) -> bool:
        """Graceful give-back: a client closing with unconsumed records hands
        its task back to the todo queue WITHOUT burning a failure event —
        deliberate abandonment (early stop, capped test pass) is not a crash,
        and must not walk the task toward the failure_max discard."""
        with self._lock:
            ent = self.pending.get(task_id)
            if ent is None or ent[0].epoch != epoch:
                return False
            del self.pending[task_id]
            self.todo.append(ent[0])
            self._snapshot()
            return True

    def _process_failed(self, task: Task) -> None:
        """epoch++, discard past failure_max, else requeue (service.go:308)."""
        self.fail_events += 1
        task.epoch += 1
        if task.epoch >= self.failure_max:
            self.discarded.append(task)  # discard (service.go:336)
        else:
            self.todo.append(task)

    def _requeue_expired(self) -> None:
        now = self._clock()
        expired = [tid for tid, ent in self.pending.items() if ent[1] < now]
        for tid in expired:
            task = self.pending.pop(tid)[0]
            self._process_failed(task)

    # -- elastic cluster plane: registry / fences / results ---------------
    def register_worker(self, worker_id: str) -> Dict[str, Any]:
        """Join (or rejoin) the worker registry under a heartbeat lease.
        Returns the cluster view the worker needs to enter the pass loop —
        idempotent, so a worker that outlived a master failover (the new
        leader recovers queues from the snapshot but the registry is
        runtime state) just re-registers."""
        with self._lock:
            self._prune_workers()
            self.workers[worker_id] = self._clock() + self.worker_timeout_s
            return {
                "pass_id": self.pass_id,
                "timeout_s": self.worker_timeout_s,
                "auto_rotate": self.auto_rotate,
                "workers": sorted(self.workers),
            }

    def heartbeat(self, worker_id: str) -> bool:
        """Renew the registry lease; False means the worker expired (or the
        master failed over) and must ``register_worker`` again."""
        with self._lock:
            self._prune_workers()
            if worker_id not in self.workers:
                return False
            self.workers[worker_id] = self._clock() + self.worker_timeout_s
            return True

    def deregister_worker(self, worker_id: str) -> None:
        """Graceful leave: held task leases go back to todo WITHOUT a
        failure event (the task_returned discipline — leaving is not a
        crash)."""
        with self._lock:
            self.workers.pop(worker_id, None)
            held = [
                tid for tid, ent in self.pending.items() if ent[2] == worker_id
            ]
            for tid in held:
                self.todo.append(self.pending.pop(tid)[0])
            if held:
                self._snapshot()

    def live_workers(self) -> List[str]:
        with self._lock:
            self._prune_workers()
            return sorted(self.workers)

    def _prune_workers(self) -> None:
        """Expire silent workers and requeue their task leases NOW — the
        kill-one-of-N path: a dead worker costs one registry lease timeout,
        not the job (and not even the longer per-task lease timeout)."""
        now = self._clock()
        dead = [w for w, dl in self.workers.items() if dl < now]
        for w in dead:
            del self.workers[w]
            held = [tid for tid, ent in self.pending.items() if ent[2] == w]
            for tid in held:
                self._process_failed(self.pending.pop(tid)[0])
            if held:
                self._snapshot()

    def fence_arrive(
        self, fence_id: str, worker_id: str,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Arrive at a barrier.  The fence releases once every LIVE worker
        has arrived (membership is evaluated per poll, so a worker that
        died — and was pruned — never wedges the boundary).  Release
        freezes the arrived set and the done-task count: late arrivals see
        the frozen view and can tell they missed the membership cut.

        ``meta`` declares per-worker capabilities; ``{"ckpt": True}`` opts
        the worker into the frozen ``writers`` set, so the shard-writer
        roster is negotiated among checkpoint-enabled workers rather than
        assumed equal to the whole membership (one checkpoint-less worker
        must not doom every manifest commit)."""
        with self._lock:
            f = self.fences.setdefault(
                fence_id, {"arrived": set(), "released": None, "meta": {}}
            )
            if f["released"] is None:
                f["arrived"].add(worker_id)
                if meta:
                    f["meta"][worker_id] = dict(meta)
            if worker_id in self.workers:
                # arriving (and re-arriving while polling) is a liveness
                # signal: renew so a worker parked at a slow barrier is
                # never pruned mid-wait.  Renew-only — a PRUNED worker
                # re-joins through register_worker/get_task, keeping the
                # missed-the-membership-cut semantics observable.
                self.workers[worker_id] = self._clock() + self.worker_timeout_s
            if len(self.fences) > 64:  # bound runtime state
                for stale in list(self.fences)[: len(self.fences) - 64]:
                    if stale != fence_id:
                        del self.fences[stale]
            return self._fence_view(fence_id)

    def fence_status(self, fence_id: str) -> Dict[str, Any]:
        with self._lock:
            return self._fence_view(fence_id)

    def _fence_view(self, fence_id: str) -> Dict[str, Any]:
        f = self.fences.get(fence_id)
        if f is None:
            return {"known": False, "released": False}
        if f["released"] is None:
            self._prune_workers()
            members = None
            if self.workers and set(self.workers) <= f["arrived"]:
                members = sorted(f["arrived"] & set(self.workers))
            elif not self.workers and f["arrived"]:
                # no registry (legacy/single-worker use): whoever arrived
                # is the membership
                members = sorted(f["arrived"])
            if members is not None:
                f["released"] = {
                    "workers": members,
                    "writers": [
                        w for w in members
                        if f["meta"].get(w, {}).get("ckpt")
                    ],
                    "n_done": len(self.done),
                    "pass_id": self.pass_id,
                }
        if f["released"] is None:
            return {
                "known": True, "released": False,
                "n_arrived": len(f["arrived"]),
            }
        return {"known": True, "released": True, **f["released"]}

    def pass_results(self, pass_id: int) -> Dict[str, Any]:
        """``{"results": {task_id: payload}, "n_done": int|None}`` for one
        pass — every worker reduces the map in sorted task-id order, so the
        update is bit-identical fleet-wide regardless of which worker
        computed which task.  ``n_done`` is the pass's frozen done count
        once it rotated (None while the pass is still current — the fence
        view carries the authoritative count there): a late joiner replays
        a retained pass only when ``len(results) == n_done``."""
        with self._lock:
            return {
                "results": dict(self.results.get(pass_id, {})),
                "n_done": self._pass_done.get(pass_id),
            }

    def requeue_unresulted(self) -> int:
        """Move done tasks that have NO stored result for the current pass
        back to todo.  After a master failover the queue snapshot survives
        but the in-memory result payloads do not; recomputing the orphaned
        tasks is safe because contributions are deterministic per task.
        Returns the number requeued.  (Never call this from the legacy
        record-streaming flow — its done tasks legitimately carry no
        results.)"""
        with self._lock:
            have = self.results.get(self.pass_id, {})
            orphaned = [t for t in self.done if t.task_id not in have]
            if orphaned:
                self.done = [t for t in self.done if t.task_id in have]
                self.todo.extend(orphaned)
                self._snapshot()
            return len(orphaned)

    def stats(self) -> Dict[str, Any]:
        """Cluster-plane observability snapshot (cheap, lock-consistent)."""
        with self._lock:
            self._prune_workers()
            return {
                "pass_id": self.pass_id,
                "n_todo": len(self.todo),
                "n_pending": len(self.pending),
                "n_done": len(self.done),
                "n_discarded": len(self.discarded),
                "fail_events": self.fail_events,
                "workers": sorted(self.workers),
            }

    # -- save-model arbitration (reference service.go:461-497) -----------
    def request_save_model(self, trainer_id: str, block_secs: float) -> bool:
        """Exactly one trainer in each window gets True."""
        with self._lock:
            now = self._clock()
            if self._save_holder and self._save_holder[1] > now:
                return self._save_holder[0] == trainer_id
            self._save_holder = (trainer_id, now + block_secs)
            return True

    # -- snapshot / recover (reference service.go:165-273, etcd → file) --
    def fence(self) -> None:
        """Stop this (deposed) Service from ever writing the shared snapshot
        again and cancel any pending debounced flush — a new leader owns the
        file now (the etcd design gets this for free from leases on keys)."""
        with self._lock:
            self.snapshot_path = None
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None

    def _snapshot(self, force: bool = False) -> None:
        """Debounced: per-task transitions at most one write per
        snapshot_min_interval_s; a skipped write is flushed by a timer so the
        last transition of a burst always reaches disk.  Structural changes
        (set_dataset, pass rotation) always write."""
        if not self.snapshot_path:
            return
        now = time.time()
        if not force and now - self._last_snapshot < self.snapshot_min_interval_s:
            if self._flush_timer is None:
                t = threading.Timer(self.snapshot_min_interval_s, self._flush)
                t.daemon = True
                self._flush_timer = t
                t.start()
            return
        self._last_snapshot = now
        self._write_snapshot()

    def _flush(self) -> None:
        with self._lock:
            self._flush_timer = None
            if not self.snapshot_path:
                return  # fenced between schedule and fire
            self._last_snapshot = time.time()
            self._write_snapshot()

    def _write_snapshot(self) -> None:
        state = {
            "pass_id": self.pass_id,
            "todo": [t.to_json() for t in self.todo],
            "pending": [
                {"task": t.to_json(), "deadline": dl, "owner": owner}
                for (t, dl, owner) in self.pending.values()
            ],
            "done": [t.to_json() for t in self.done],
            "discarded": [t.to_json() for t in self.discarded],
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)

    def _recover(self) -> None:
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self.pass_id = state["pass_id"]
        self.todo = [Task.from_json(t) for t in state["todo"]]
        self.done = [Task.from_json(t) for t in state["done"]]
        self.discarded = [Task.from_json(t) for t in state.get("discarded", [])]
        # pending leases do not survive a master restart: requeue immediately
        # (the reference instead waits for timeout; restart is the slow path)
        for ent in state["pending"]:
            self.todo.append(Task.from_json(ent["task"]))


def reader_over(next_record_fn):
    """Reader-creator over a next_record callable: one call = one pass
    (shared by Client and master_ha.HAClient)."""

    def _reader():
        while True:
            rec = next_record_fn()
            if rec is None:
                return
            yield rec

    return _reader


# ---------------------------------------------------------------------------
# RPC layer
# ---------------------------------------------------------------------------

_METHODS = ("set_dataset", "get_task", "task_finished", "task_failed",
            "task_returned", "renew_lease", "request_save_model", "n_tasks",
            "start_new_pass",
            # elastic cluster plane
            "register_worker", "heartbeat", "deregister_worker",
            "live_workers", "fence_arrive", "fence_status", "pass_results",
            "requeue_unresulted", "stats")


class Server:
    """Serve a Service over multiprocessing.connection — the process/network
    boundary of the Go master's net/rpc server."""

    def __init__(self, service: Service, address=("127.0.0.1", 0), authkey=b"paddle-tpu"):
        self.service = service
        self._authkey = authkey
        self._listener = Listener(address, authkey=authkey)
        self.address = self._listener.address
        self._stop = False
        self._conns: List = []
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn = self._listener.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.append(conn)
            if self._stop:  # closed while accepting: don't serve it
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn) -> None:
        try:
            while not self._stop:  # deposed leader: stop serving stale state
                method, args = conn.recv()
                if method == "__close__":
                    return
                if method not in _METHODS:
                    conn.send((False, f"no such method {method}"))
                    continue
                try:
                    conn.send((True, getattr(self.service, method)(*args)))
                except Exception as exc:  # noqa: BLE001 — RPC boundary
                    conn.send((False, repr(exc)))
        except (EOFError, OSError, TypeError, AttributeError):
            # TypeError/AttributeError: Server.close() closed this conn while
            # recv() was blocked (multiprocessing nulls the handle mid-read)
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def close(self) -> None:
        """Stop accepting AND drop live per-connection handler threads — a
        deposed HA leader must not keep serving stale state to connected
        clients.  The accept loop is WOKEN with a dummy connection before
        the listener closes: a thread blocked in accept() holds the
        listening socket open past Listener.close(), which would keep the
        port bound and break a master restarting on its own address."""
        self._stop = True
        try:
            _ConnClient(tuple(self.address), authkey=self._authkey).close()
        except Exception:  # noqa: BLE001 — wake-up is best effort
            pass
        self._listener.close()
        self._thread.join(timeout=5)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class Client:
    """python/paddle/v2/master/client.py parity: set_dataset + next_record.

    `master` is either an in-process Service or a (host, port) address of a
    Server.  Records stream task-by-task; at a pass boundary next_record
    returns None once (like the reference's empty-record pass signal)."""

    def __init__(
        self,
        master,
        authkey: bytes = b"paddle-tpu",
        trainer_id: str = "0",
        reconnect_tries: int = 5,
        reconnect_backoff: float = 0.1,
    ):
        if isinstance(master, Service):
            self._service = master
            self._conn = None
        else:
            self._service = None
            self._address = tuple(master)
            self._authkey = authkey
            self._conn = _ConnClient(self._address, authkey=authkey)
            self._conn_lock = threading.Lock()
        self.reconnect_tries = max(int(reconnect_tries), 1)
        self.reconnect_backoff = float(reconnect_backoff)
        self.trainer_id = trainer_id
        self._records: List[bytes] = []
        self._pending_task = None  # (task_id, epoch) awaiting ack-on-drain
        self._last_renew = 0.0
        self.lease_renew_secs = 10.0  # renewal throttle ceiling
        self._renew_interval = self.lease_renew_secs

    def _call(self, method: str, *args):
        """One RPC.  Transient TRANSPORT failures (connection reset / EOF on
        the pipe — a master restarting, a dropped socket) get a short
        reconnect-retry with exponential backoff before surfacing as
        :class:`MasterTransportError`; the retried call is re-sent whole
        (every master method is idempotent-or-epoch-guarded, so an
        at-least-once duplicate is absorbed server-side).  Application
        errors surface as :class:`MasterRPCError` immediately — the master
        EXECUTED the call; retrying a deterministic failure is futile."""
        if self._service is not None:
            return getattr(self._service, method)(*args)
        last_err: Optional[Exception] = None
        with self._conn_lock:
            for attempt in range(self.reconnect_tries):
                try:
                    if self._conn is None:
                        self._conn = _ConnClient(
                            self._address, authkey=self._authkey
                        )
                    self._conn.send((method, args))
                    ok, result = self._conn.recv()
                    break
                except (ConnectionError, EOFError, OSError) as exc:
                    last_err = exc
                    if self._conn is not None:
                        try:
                            self._conn.close()
                        except OSError:
                            pass
                        self._conn = None
                    if attempt + 1 >= self.reconnect_tries:
                        raise MasterTransportError(
                            f"master RPC {method}: transport failed after "
                            f"{self.reconnect_tries} attempt(s): {exc!r}"
                        ) from exc
                    time.sleep(self.reconnect_backoff * (2 ** attempt))
        if not ok:
            raise MasterRPCError(f"master RPC {method} failed: {result}")
        return result

    # -- surface ---------------------------------------------------------
    def set_dataset(self, patterns: Sequence[str]) -> int:
        return self._call("set_dataset", list(patterns))

    def request_save_model(self, block_secs: float = 60.0) -> bool:
        return self._call("request_save_model", self.trainer_id, block_secs)

    def start_new_pass(self, target_pass: Optional[int] = None) -> int:
        return self._call("start_new_pass", target_pass)

    def __getattr__(self, name: str):
        """Every other RPC method (the elastic cluster surface — get_task,
        task_finished(task, epoch, result), register_worker/heartbeat,
        fence_arrive/fence_status, pass_results, requeue_unresulted,
        stats, ...) delegates positionally straight from ``_METHODS`` —
        ONE definition instead of a hand-kept mirror per client class.
        Signatures/semantics are the Service methods'."""
        if name in _METHODS:
            return lambda *args: self._call(name, *args)
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}"
        )

    def next_record(self) -> Optional[bytes]:
        """The next record of the current task, fetching a new task when the
        current one drains; None exactly at a pass boundary."""
        if self._records and self._pending_task is not None:
            # Renew the held lease while the trainer drains (throttled to a
            # fraction of the server's lease timeout): a consumer slower than
            # the lease timeout must not trip the failure/discard path.  A
            # failed renewal means the task already expired and was re-served
            # elsewhere — keep serving the buffer (at-least-once duplicates),
            # the epoch-guarded ack below is then a harmless no-op.
            now = time.time()
            if now - self._last_renew >= self._renew_interval:
                self._last_renew = now
                self._call("renew_lease", *self._pending_task)
        while not self._records:
            # Consume-then-ack (at-least-once, reference go/master client
            # semantics): the previous task is finished only once every one
            # of its records has been handed to the trainer, so a crash
            # mid-consumption re-serves the task instead of losing it.
            if self._pending_task is not None:
                self._call("task_finished", *self._pending_task)
                self._pending_task = None
            got = self._call("get_task")
            if got is None:
                return None
            if got == "wait":  # other workers hold the remaining leases
                time.sleep(0.01)
                continue
            fetched: List[bytes] = []
            try:
                for c in got["task"]["chunks"]:
                    with recordio.Reader(c["path"], offset=c["offset"]) as r:
                        for _ in range(c["n_records"]):
                            rec = r.next()
                            if rec is None:
                                break
                            fetched.append(rec)
            except IOError:
                self._call("task_failed", got["task"]["task_id"], got["epoch"])
                continue
            # Lease is held until drain (renewed above while consuming); a
            # crash mid-consumption re-serves the task (duplicates are
            # possible, loss is not).
            self._pending_task = (got["task"]["task_id"], got["epoch"])
            self._last_renew = time.time()
            # Renew well before the server-side lease expires.
            self._renew_interval = min(
                self.lease_renew_secs, got.get("timeout_s", 60.0) / 3.0
            )
            self._records = fetched
        return self._records.pop(0)

    def reader(self):
        """A reader-creator over next_record for the v2 trainer: one call =
        one pass."""
        return reader_over(self.next_record)

    def close(self) -> None:
        # Release a held lease: ack if the buffer drained, otherwise hand the
        # task back (no failure event) so the records re-serve this pass
        # instead of expiring into the failure/discard path.
        if self._pending_task is not None:
            try:
                if self._records:
                    self._call("task_returned", *self._pending_task)
                else:
                    self._call("task_finished", *self._pending_task)
            except (RuntimeError, BrokenPipeError, OSError, EOFError):
                pass
            self._pending_task = None
            self._records = []
        if self._conn is not None:
            try:
                self._conn.send(("__close__", ()))
            except (BrokenPipeError, OSError):
                pass
            self._conn.close()
